"""HTTP decode server: the TPU-native analogue of an SGLang/vLLM server.

Wraps a `JaxDecodeEngine` behind the JSON-over-HTTP control plane that
`RemoteInfEngine` speaks. Parity targets: the server side of
areal/engine/sglang_remote.py (endpoint set) and
areal/launcher/sglang_server.py (subprocess wrapper: health wait +
name_resolve registration).

Endpoints:
  GET  /health                  -> {"status": "ok", "version": N}
  GET  /info                    -> model/config metadata
  POST /generate                -> one completion w/ token logprobs+versions;
                                   an optional "xid" delivery id makes the
                                   call idempotent: a retry of an in-flight
                                   submission awaits the SAME engine future
                                   and a replay of a completed one returns
                                   the cached response (exactly-once under
                                   client retry + router failover-requeue)
  POST /pause_generation        -> pause on chunk boundary; {"abort": true}
                                   flushes in-flight requests, which return
                                   stop_reason="interrupt" (partial rollout)
  POST /continue_generation
  POST /update_weights_from_disk  {"path": ..., "version": optional}
  POST /update_weights_from_tensor?push_id=ID   framed weight bucket; stages
                                   with generation LIVE (no pause)
  POST /commit_weights            {"version", "push_id", "lora_scale"?} —
                                   the only pause window: install + stamp
                                   version atomically; stale push_id -> 409
  POST /abort_weights             {"push_id"} — drop staging for a failed push
  POST /set_version               {"version": N}

Disaggregated prefill/decode (--role {unified,prefill,decode}):

  POST /prefill                   run ONLY the prompt prefill (body like
                                   /generate + optional "target" decode
                                   replica + "xid"); the parked session is
                                   then streamed server→server to the
                                   target over the KV wire format, where
                                   it lands in the host tier and the
                                   client's /generate resumes it with
                                   ZERO re-prefill. Transfer failures
                                   degrade: the decode replica simply
                                   re-prefills (honest miss).
  POST /kv_recv?xid=ID            one framed KV bucket (pack_kv_session);
                                   staged per-xid with interval-merged
                                   coverage — duplicate/re-split retry
                                   frames are safe, torn frames are
                                   rejected before a byte stages
  POST /kv_commit                 {"xid"} — finalize + import the staged
                                   session(s); idempotent per xid (a
                                   retried commit replays the cached
                                   result, never double-imports)
  POST /drain                     {"targets": [addr...]} — park in-flight
                                   generations (clients resume via their
                                   interrupt loop) and stream every
                                   parked + host-tier session to the
                                   targets: scale-down without losing a
                                   single session to re-prefill

Generation runs on the engine's background scheduler thread; the aiohttp
loop only brokers futures, so thousands of streams multiplex over one
static-shape decode program.
"""

from __future__ import annotations

import argparse
import asyncio
import dataclasses
import os
import socket
import time
from collections import OrderedDict
from typing import Any

from aiohttp import web

from areal_tpu.api.cli_args import (
    GenerationHyperparameters,
    InferenceEngineConfig,
    JaxDecodeConfig,
)
from areal_tpu.api.io_struct import ModelRequest, WeightUpdateMeta
from areal_tpu.core import fault_injection, kv_fabric
from areal_tpu.utils import logging, names
from areal_tpu.utils import name_resolve

logger = logging.getLogger("decode_server")

_GCONFIG_FIELDS = {f.name for f in dataclasses.fields(GenerationHyperparameters)}


def _parse_gconfig(d: dict[str, Any]) -> GenerationHyperparameters:
    return GenerationHyperparameters(
        **{k: v for k, v in d.items() if k in _GCONFIG_FIELDS}
    )


class DecodeServer:
    def __init__(
        self,
        config: JaxDecodeConfig,
        inference_config: InferenceEngineConfig | None = None,
        tokenizer: Any = None,
        engine: Any = None,
        shutdown_grace: float = 5.0,
    ):
        from areal_tpu.engine.jax_decode import JaxDecodeEngine

        self.config = config
        # how long stop() waits for in-flight handlers before cancelling
        # them (aiohttp shutdown_timeout); short so a killed replica's
        # clients fail fast into their router-aware failover retry
        self.shutdown_grace = shutdown_grace
        self.engine = engine or JaxDecodeEngine(
            config, inference_config or InferenceEngineConfig(), tokenizer
        )
        self._owns_engine = engine is None
        self._runner: web.AppRunner | None = None
        self.addr: str | None = None
        # Threading model (docs/architecture.md "Threading model and lock
        # hierarchy"): every handler runs on ONE aiohttp event loop, so
        # handler-local state below is single-threaded between awaits;
        # critical sections that span an await (pause/commit windows) are
        # serialized by _ctl_lock. areal-lint (AR101) models async handlers
        # as one "eventloop" context for the same reason.
        # Set by /pause_generation, cleared by /continue_generation: a weight
        # update must not cancel a pause the client asked for explicitly.
        self._client_paused = False  # guarded-by: _ctl_lock
        # Serialises pause/continue/weight-swap: a /continue_generation must
        # not resume decoding in the middle of an in-flight swap, or tokens
        # from the new weights would carry the old version stamp.
        self._ctl_lock = asyncio.Lock()
        # Buckets staged by /update_weights_from_tensor until /commit_weights.
        from areal_tpu.core.weight_transfer import WeightStaging

        self._weight_staging = WeightStaging()  # guarded-by: _ctl_lock
        self._staging_push_id: str | None = None  # guarded-by: _ctl_lock
        self._staging_t0: float | None = None  # guarded-by: _ctl_lock
        # last frame arrival for the crash-mid-stage reaper: staging whose
        # feed went silent for weight_staging_ttl_s is dropped (push-id
        # epoch cleared) the next time a weight endpoint runs
        self._staging_last_frame_t: float | None = None  # guarded-by: _ctl_lock
        self._last_commit_version: int | None = None  # guarded-by: _ctl_lock
        self._last_commit_push_id: str | None = None  # guarded-by: _ctl_lock
        # weight-sync observability (server side); merged into /metrics.
        # /metrics reads it without _ctl_lock: the read happens between
        # awaits on the same loop, so it observes an atomic snapshot.
        self._sync_stats = dict(  # guarded-by: _ctl_lock
            n_pushes=0,
            wire_bytes=0,
            # bf16-equivalent bytes of the frames received — raw/sent is
            # the int8 weight-serving compression ratio (ISSUE 16)
            wire_bytes_raw=0,
            staging_secs=0.0,
            commit_pause_secs=0.0,
            aborted_pushes=0,
            reaped_pushes=0,
        )
        # Idempotency table (exactly-once failover, ISSUE 8): xid ->
        # {"done": False, "fut": Future} while a submission is in flight,
        # {"done": True, "resp": dict, "t": monotonic} afterwards. All
        # reads/writes happen on the one aiohttp event loop with no await
        # between check-and-insert, so the table needs no lock; duplicates
        # await the in-flight future via asyncio.shield (a shed duplicate
        # must not cancel the original generation). Bounded by
        # config.idempotency_entries (LRU) + idempotency_ttl_s (completed
        # entries only — in-flight ones are naturally bounded by the
        # engine's concurrency).
        self._idem: "OrderedDict[str, dict[str, Any]]" = OrderedDict()
        self._idem_hits = 0
        # -- cross-replica KV migration state (ISSUE 10) ----------------
        # All accessed only between awaits on the one aiohttp event loop
        # (same single-context argument as _idem above — no lock needed).
        # Per-xid staging for inbound KV sessions: the sender may re-send
        # every frame on a retry; WeightStaging's interval-merged coverage
        # absorbs duplicates, and a torn frame is rejected before staging.
        self._kv_staging: "OrderedDict[str, dict[str, Any]]" = OrderedDict()
        # xid -> completed /kv_commit response: a retried commit (sender
        # replaying a migration whose response was lost) returns the
        # cached result instead of importing twice — the exactly-once
        # half the sender's full-stream replay relies on.
        self._kv_done: "OrderedDict[str, dict[str, Any]]" = OrderedDict()
        self._migrate_stats = dict(
            out_sessions=0,
            out_bytes=0,
            out_failures=0,
            in_frames=0,
            in_commits=0,
            commit_dedups=0,
            transfer_secs=0.0,
        )
        # In-progress /drain guard (drains are serialized per server):
        # while a drain runs, this holds its result future; concurrent
        # /drain calls await it and replay the first result instead of
        # double-exporting the same sessions. Claimed with no await after
        # the done-check, so the check-and-set is event-loop-atomic.
        self._drain_inflight: asyncio.Future | None = None
        # -- fleet KV fabric (ISSUE 17) ---------------------------------
        # Outbound-fetch dedup: concurrent /generate's carrying the same
        # router hint await ONE peer fetch instead of each pulling the
        # same blocks (event-loop-atomic claim, like _idem). Stats merge
        # into /metrics under "kv_fabric".
        self._fabric_inflight: dict[str, asyncio.Future] = {}
        self._fabric_stats = dict(
            fetch_attempts=0,
            fetch_sessions=0,
            fetch_bytes=0,
            fetch_failures=0,
            serve_sessions=0,
            serve_bytes=0,
            warm_start_sessions=0,
            warm_start_bytes=0,
        )

    # -- handlers -------------------------------------------------------
    async def _health(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "status": "ok",
                "version": self.engine.get_version(),
                # the router's role-aware scheduler reads this: prefill
                # replicas are picked by prefix affinity, decode replicas
                # by kv-pool headroom
                "role": getattr(self.config, "role", "unified"),
            }
        )

    async def _info(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "model_path": self.config.model_path,
                "role": getattr(self.config, "role", "unified"),
                "kv_migrate_chunk_mb": getattr(
                    self.config, "kv_migrate_chunk_mb", 64.0
                ),
                "context_length": self.config.context_length,
                "max_running_requests": self.config.max_running_requests,
                "decode_runahead_chunks": self.config.decode_runahead_chunks,
                "kv_layout": self.config.kv_layout,
                "kv_dtype": getattr(self.config, "kv_dtype", "fp"),
                "weight_dtype": getattr(self.config, "weight_dtype", "fp"),
                "kv_host_pool_mb": self.config.kv_host_pool_mb,
                "paged_attn_impl": self.config.paged_attn_impl,
                "spec_decode": self.config.spec_decode,
                "spec_k": self.config.spec_k,
                "spec_ngram_max": self.config.spec_ngram_max,
                "version": self.engine.get_version(),
            }
        )

    def _prune_idem(self) -> None:
        now = time.monotonic()
        ttl = self.config.idempotency_ttl_s
        for xid in list(self._idem):
            ent = self._idem[xid]
            if ent["done"] and now - ent["t"] > ttl:
                del self._idem[xid]
        while len(self._idem) > max(1, self.config.idempotency_entries):
            # oldest completed entry first; in-flight entries only under
            # pathological pressure (they are few: engine concurrency)
            victim = next(
                (x for x, e in self._idem.items() if e["done"]),
                next(iter(self._idem)),
            )
            del self._idem[victim]

    async def _generate(self, request: web.Request) -> web.Response:
        body = await request.json()
        xid = body.get("xid")
        # pre-effect seam: an abort here rejects the request before any
        # engine state moves (clean client retry); a delay is the
        # slow-replica shape the router's circuit breaker must absorb
        await fault_injection.afire(
            "server.generate",
            rid=str(body.get("rid") or ""), xid=str(xid or ""),
            addr=str(self.addr or ""),
        )
        if xid is not None:
            ent = self._idem.get(xid)
            if ent is not None:
                # duplicate delivery (client transport retry, or a retry
                # after failover raced the original): never re-generate
                self._idem_hits += 1
                if ent["done"]:
                    self._idem.move_to_end(xid)
                    return web.json_response(
                        {**ent["resp"], "dedup": "completed"}
                    )
                out = await asyncio.shield(ent["fut"])
                return web.json_response({**out, "dedup": "in_progress"})
            ent = {
                "done": False,
                "fut": asyncio.get_running_loop().create_future(),
                "t": time.monotonic(),
            }
            self._idem[xid] = ent
        hint = body.get("kv_fabric")
        if hint and getattr(self.config, "kv_fabric", True):
            # router says a sibling holds this prefix: pull the block
            # runs into the host tier before admission looks for them
            await self._fabric_prefetch(hint)
        req = ModelRequest(
            rid=body.get("rid") or ModelRequest().rid,
            input_ids=[int(t) for t in body["input_ids"]],
            gconfig=_parse_gconfig(body.get("gconfig", {})),
            image_data=body.get("image_data"),
        )
        try:
            resp = await self.engine.agenerate(req)
        except BaseException as e:
            if xid is not None and self._idem.get(xid) is ent:
                del self._idem[xid]
                if not ent["fut"].done():
                    ent["fut"].set_exception(e)
                    # mark retrieved: with no duplicate awaiting, an
                    # unconsumed future exception would log noise
                    ent["fut"].exception()
            raise
        out = {
            "output_tokens": resp.output_tokens,
            "output_logprobs": resp.output_logprobs,
            "output_versions": resp.output_versions,
            "stop_reason": resp.stop_reason,
            "latency": resp.latency,
            "ttft": resp.ttft,
            "itl": resp.itl,
        }
        if xid is not None and self._idem.get(xid) is ent:
            self._idem[xid] = {"done": True, "resp": out, "t": time.monotonic()}
            self._idem.move_to_end(xid)
            if not ent["fut"].done():
                ent["fut"].set_result(out)
            self._prune_idem()
        return web.json_response(out)

    async def _metrics(self, request: web.Request) -> web.Response:
        """Live engine load counters (running/queued requests, active KV
        tokens, generated-token totals, prefix-cache hit mix) plus the
        decode-loop timing split (itl_p50_ms/itl_p99_ms: device-only
        inter-token latency; device_idle_frac: host-gap fraction the
        run-ahead scheduler hides). The router's least_token_usage policy
        polls this — parity with the per-server token accounting of
        realhf/system/gserver_manager.py:261-339."""
        get = getattr(self.engine, "get_metrics", None)
        if get is None:
            # 404, not {}: the router must fall back to its own estimates
            # rather than record a phantom zero load
            raise web.HTTPNotFound(reason="engine exports no metrics")
        out = dict(get())
        ws = dict(self._sync_stats, staged_tensors=len(self._weight_staging))
        ws["wire_bytes_sent"] = ws["wire_bytes"]
        # raw/sent: 1.0 on fp pushes, ~2x once the producer ships int8
        # kernels (weight_transfer.raw_wire_nbytes)
        ws["weight_sync_compression"] = (
            round(ws["wire_bytes_raw"] / ws["wire_bytes_sent"], 4)
            if ws["wire_bytes_sent"]
            else 1.0
        )
        out["weight_sync"] = ws
        # rid-dedup observability: table occupancy + duplicate deliveries
        # prevented (the exactly-once evidence bench --mode fleet reads)
        out["idem_entries"] = len(self._idem)
        out["idem_hits_total"] = self._idem_hits
        # KV-migration observability (server side): sessions/bytes
        # streamed out, inbound frames/commits, commit dedups (the
        # exactly-once evidence), and abandoned transfers (degraded to
        # re-prefill). The engine's own kv_migrated_* counters sit next
        # to these at the top level.
        out["kv_migrate"] = dict(
            self._migrate_stats,
            staging_xids=len(self._kv_staging),
            done_xids=len(self._kv_done),
        )
        # fleet KV fabric (server side): prefetches issued/served, bytes
        # moved, failures (each one a degraded-to-local-prefill), and
        # warm-start pulls. Engine-side kv_fabric_* counters (hits,
        # tokens avoided, digest) are already in `out`.
        out["kv_fabric"] = dict(
            self._fabric_stats, inflight=len(self._fabric_inflight)
        )
        return web.json_response(out)

    async def _pause(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except Exception as e:  # noqa: BLE001 — body is optional
            logger.debug(f"/pause body ignored: {e!r}")
            body = {}
        # pause_generation blocks until the scheduler is idle — run it off
        # the event loop so in-flight /generate futures can resolve.
        async with self._ctl_lock:
            self._client_paused = True
            await asyncio.get_running_loop().run_in_executor(
                None, self.engine.pause_generation
            )
            aborted = 0
            if body.get("abort"):
                aborted = self.engine.abort_all()
        return web.json_response({"status": "ok", "aborted": aborted})

    async def _continue(self, request: web.Request) -> web.Response:
        async with self._ctl_lock:
            self._client_paused = False
            self.engine.continue_generation()
        return web.json_response({"status": "ok"})

    async def _update_weights_from_disk(
        self, request: web.Request
    ) -> web.Response:
        body = await request.json()
        meta = WeightUpdateMeta(type="disk", path=body["path"])
        version = body.get("version")

        def _swap():
            # Hold the pause across swap + version bump so no token is ever
            # produced by the new weights under the old version stamp.
            self.engine.pause_generation()
            try:
                self.engine.update_weights_from_disk(meta)
                if version is not None:
                    self.engine.set_version(int(version))
            finally:
                if not self._client_paused:
                    self.engine.continue_generation()

        async with self._ctl_lock:
            await asyncio.get_running_loop().run_in_executor(None, _swap)
        return web.json_response(
            {"status": "ok", "version": self.engine.get_version()}
        )

    async def _set_version(self, request: web.Request) -> web.Response:
        body = await request.json()
        self.engine.set_version(int(body["version"]))
        return web.json_response({"status": "ok"})

    # -- "dcn" in-memory weight push (areal_tpu/core/weight_transfer.py) --
    # Buckets stage with generation LIVE (the handler never pauses the
    # engine — the scheduler thread keeps emitting tokens while bytes
    # accumulate); only the commit's install pays a pause, inside
    # engine.update_weights_from_tensor.
    def _reap_stale_staging_locked(self) -> None:
        """Crash-mid-stage recovery (caller holds _ctl_lock): a push whose
        frame feed went silent for `weight_staging_ttl_s` is dead — its
        learner crashed or lost connectivity mid-stage. Drop the staging
        and clear the push-id epoch so the next push starts clean instead
        of multi-GiB zombie staging lingering until an operator notices.
        (The client independently aborts its own incomplete push on
        reconnect; this reaper covers clients that never come back.)"""
        ttl = self.config.weight_staging_ttl_s
        if ttl <= 0 or self._staging_last_frame_t is None:
            return
        if time.monotonic() - self._staging_last_frame_t <= ttl:
            return
        if len(self._weight_staging._bufs) or len(self._weight_staging):
            logger.warning(
                f"reaping stale weight staging (push {self._staging_push_id}, "
                f"silent > {ttl:.0f}s)"
            )
            self._sync_stats["reaped_pushes"] += 1
        self._weight_staging.reset()
        self._staging_push_id = None
        self._staging_t0 = None
        self._staging_last_frame_t = None

    async def _update_weights_from_tensor(
        self, request: web.Request
    ) -> web.Response:
        payload = await request.read()
        push_id = request.query.get("push_id")
        await fault_injection.afire(
            "server.weights.stage",
            push_id=str(push_id or ""), addr=str(self.addr or ""),
        )
        async with self._ctl_lock:
            self._reap_stale_staging_locked()
            # Push ids are timestamp-ordered (remote_inf_engine): a NEWER id
            # invalidates whatever a previous (failed / abandoned) push left
            # behind; an OLDER id is a stale straggler frame whose retry
            # must stop rather than wipe the current push's staging.
            if push_id is not None:
                cur = self._staging_push_id
                if cur is not None and push_id < cur:
                    return web.json_response(
                        {"status": "error", "message": "stale push_id"},
                        status=409,
                    )
                if push_id != cur:
                    self._weight_staging.reset()
                    self._staging_push_id = push_id
                    self._staging_t0 = time.monotonic()
            elif self._staging_t0 is None:
                self._staging_t0 = time.monotonic()
            self._weight_staging.add_bucket(payload)
            self._staging_last_frame_t = time.monotonic()
            self._sync_stats["wire_bytes"] += len(payload)
            # after add_bucket: a torn frame raised above, so the manifest
            # parsed here is the one whose bytes were actually staged
            from areal_tpu.core.weight_transfer import frame_raw_nbytes

            self._sync_stats["wire_bytes_raw"] += frame_raw_nbytes(payload)
        return web.json_response(
            {"status": "ok", "staged": len(self._weight_staging)}
        )

    async def _commit_weights(self, request: web.Request) -> web.Response:
        body = await request.json()
        version = body.get("version")
        push_id = body.get("push_id")
        lora_scale = body.get("lora_scale")
        await fault_injection.afire(
            "server.weights.commit",
            push_id=str(push_id or ""), addr=str(self.addr or ""),
        )
        async with self._ctl_lock:
            self._reap_stale_staging_locked()
            # Version fence: a commit may only land for the push whose
            # buckets are currently staged. A commit carrying a stale
            # push_id (its staging was superseded or aborted) must be
            # rejected — committing whatever newer push happens to be
            # staged would mix weight versions.
            if push_id is not None and push_id != self._staging_push_id:
                if (
                    push_id == self._last_commit_push_id
                    and version is not None
                    and self._last_commit_version == int(version)
                ):
                    # idempotent retry of an already-applied commit
                    return web.json_response(
                        {"status": "ok", "version": self.engine.get_version()}
                    )
                return web.json_response(
                    {"status": "error", "message": "stale push_id"},
                    status=409,
                )
            if not len(self._weight_staging):
                # Idempotent retry: a commit whose response got lost leaves
                # empty staging + the version already stamped — succeed.
                if (
                    version is not None
                    and self._last_commit_version == int(version)
                ):
                    return web.json_response(
                        {"status": "ok", "version": self.engine.get_version()}
                    )
                return web.json_response(
                    {"status": "error", "message": "no staged weights"},
                    status=400,
                )
            try:
                staged = self._weight_staging.finalize()

                def _install():
                    kw = {}
                    if lora_scale is not None:
                        kw["lora_scale"] = float(lora_scale)
                    self.engine.update_weights_from_tensor(
                        staged, version=version, **kw
                    )

                t_commit = time.monotonic()
                await asyncio.get_running_loop().run_in_executor(
                    None, _install
                )
                self._sync_stats["commit_pause_secs"] += (
                    time.monotonic() - t_commit
                )
            except Exception as e:
                # A wedged staging area would poison every later push —
                # clear it so the learner can retry from scratch. Malformed
                # pushes (bad names/shapes/missing lora_scale) are 400 so
                # the client surfaces the real message instead of retrying
                # a 500 into a confusing stale-push 409.
                self._weight_staging.reset()
                self._staging_push_id = None
                self._staging_t0 = None
                self._staging_last_frame_t = None
                status = 400 if isinstance(e, (ValueError, KeyError)) else 500
                return web.json_response(
                    {"status": "error", "message": str(e)}, status=status
                )
            if self._staging_t0 is not None:
                # transfer window: first bucket arrival → commit start
                self._sync_stats["staging_secs"] += (
                    t_commit - self._staging_t0
                )
                self._staging_t0 = None
            self._sync_stats["n_pushes"] += 1
            self._last_commit_version = (
                int(version) if version is not None else None
            )
            self._last_commit_push_id = push_id
            self._staging_push_id = None
            self._staging_last_frame_t = None
        return web.json_response(
            {"status": "ok", "version": self.engine.get_version()}
        )

    async def _abort_weights(self, request: web.Request) -> web.Response:
        """Explicitly drop staging for a failed/abandoned push. Without
        this, a crashed client leaves multi-GiB staging resident until the
        next push's id happens to reset it."""
        try:
            body = await request.json()
        except Exception as e:  # noqa: BLE001 — body is optional
            logger.debug(f"/abort_weights body ignored: {e!r}")
            body = {}
        push_id = body.get("push_id")
        async with self._ctl_lock:
            if push_id is not None and self._staging_push_id not in (
                None,
                push_id,
            ):
                # a newer push owns the staging area now — nothing to drop
                return web.json_response({"status": "ok", "dropped": 0})
            dropped = len(self._weight_staging._bufs) + len(
                self._weight_staging
            )
            self._weight_staging.reset()
            self._staging_push_id = None
            self._staging_t0 = None
            self._staging_last_frame_t = None
            if dropped:
                self._sync_stats["aborted_pushes"] += 1
        return web.json_response({"status": "ok", "dropped": dropped})

    # -- disaggregated prefill/decode: KV-session migration -------------
    # Transfer shape mirrors the weight push (frames -> staging -> one
    # commit) because it IS the same plumbing: pack_kv_session frames ride
    # WeightStaging's interval-merged coverage, so the sender's recovery
    # story is "replay the whole session under the same xid" — duplicate
    # frames merge, the commit dedups, and the handoff lands exactly once.
    _MIGRATE_TIMEOUT_S = 60.0
    _KV_STAGING_MAX = 64
    _KV_DONE_MAX = 1024

    def _prune_kv_maps(self) -> None:
        now = time.monotonic()
        ttl = self.config.idempotency_ttl_s
        for xid in list(self._kv_done):
            if now - self._kv_done[xid]["t"] > ttl:
                del self._kv_done[xid]
        while len(self._kv_done) > self._KV_DONE_MAX:
            self._kv_done.popitem(last=False)
        # staging whose feed went silent is a crashed sender: the replay
        # (same xid) restarts from an empty staging area harmlessly
        for xid in list(self._kv_staging):
            if now - self._kv_staging[xid]["last_t"] > ttl:
                del self._kv_staging[xid]
        while len(self._kv_staging) > self._KV_STAGING_MAX:
            victim, _ = self._kv_staging.popitem(last=False)
            logger.warning(f"kv staging {victim} dropped (map full)")

    async def _stream_kv(
        self,
        target: str,
        sess: dict[str, Any],
        rid: str,
        xid: str,
        retries: int = 2,
    ) -> dict[str, Any] | None:
        """Stream one exported session dict to `target` under delivery id
        `xid` (frames -> /kv_recv -> /kv_commit). Shared by session
        migration, fabric block fetches and warm starts — so the
        `kv.migrate.*` fault seams cover all three. Meta-only sessions
        (cheap drain) ride the same wire as a single metadata frame."""
        from areal_tpu.core.weight_transfer import pack_kv_session
        from areal_tpu.utils.http import arequest_with_retry

        frames = list(
            pack_kv_session(
                sess["meta"],
                sess.get("k"),
                sess.get("v"),
                ks=sess.get("ks"),
                vs=sess.get("vs"),
                chunk_mb=getattr(self.config, "kv_migrate_chunk_mb", 64.0),
            )
        )
        nbytes = sum(len(f) for f in frames)
        t0 = time.monotonic()
        last: Exception | None = None
        for attempt in range(retries + 1):
            try:
                for frame in frames:
                    # send seam: an abort models the sender dying
                    # mid-stream — the replay (same xid) must land the
                    # session exactly once
                    await fault_injection.afire(
                        "kv.migrate.send",
                        rid=rid, xid=xid, target=target, attempt=attempt,
                    )
                    await arequest_with_retry(
                        target,
                        f"/kv_recv?xid={xid}",
                        data=frame,
                        max_retries=2,
                        timeout=self._MIGRATE_TIMEOUT_S,
                    )
                out = await arequest_with_retry(
                    target,
                    "/kv_commit",
                    payload={"xid": xid, "rid": rid},
                    max_retries=2,
                    timeout=self._MIGRATE_TIMEOUT_S,
                )
                dt = time.monotonic() - t0
                self._migrate_stats["out_sessions"] += 1
                self._migrate_stats["out_bytes"] += nbytes
                self._migrate_stats["transfer_secs"] += dt
                return {"bytes": nbytes, "secs": dt, "commit": out}
            except Exception as e:  # noqa: BLE001 — replay, then degrade
                last = e
                if attempt < retries:
                    logger.warning(
                        f"kv migration of {rid} to {target} failed "
                        f"({e!r}); replaying under xid {xid}"
                    )
        self._migrate_stats["out_failures"] += 1
        logger.warning(
            f"kv migration of {rid} to {target} abandoned ({last!r}); "
            "the session resumes with a re-prefill"
        )
        return None

    async def _migrate_session_out(
        self,
        target: str,
        rid: str,
        xid: str,
        retries: int = 2,
        refetchable: "set[int] | None" = None,
    ) -> dict[str, Any] | None:
        """Export `rid` and stream it to `target` under delivery id `xid`.

        The export MOVES the session out of this engine first; a transfer
        that fails past its replay budget therefore degrades to a
        re-prefill on whichever replica the session resumes on — never a
        wedged handler. The budget is two full-stream replays (same xid):
        a mid-transfer sender death and a torn frame are INDEPENDENT
        failures, and a budget of one means any two of them composing on
        one session silently downgrades the handoff to a re-prefill.
        Re-sent frames interval-merge and the commit is idempotent, so
        however many replays run, the handoff lands exactly once.

        `refetchable` (cheap drain): content keys the surviving fleet can
        serve — sessions fully covered by them export meta-only (no KV
        bytes on the wire; the resume re-fetches blocks on demand)."""
        loop = asyncio.get_running_loop()
        sess = await loop.run_in_executor(
            None, self.engine.export_session, rid, refetchable
        )
        if sess is None:
            return None
        out = await self._stream_kv(target, sess, rid, xid, retries=retries)
        if out is not None:
            out["meta_only"] = bool(sess["meta"].get("meta_only"))
        return out

    # -- fleet KV fabric (content-addressed block fetch) ----------------
    async def _kv_fetch(self, request: web.Request) -> web.Response:
        """Serve content-keyed block runs to a sibling: resolve the
        requested chain (or the `top` longest resident chains, for a warm
        start) and PUSH the matching sessions to `target` over the
        migration wire. Copy semantics — nothing local is dropped; a
        failed push degrades to a re-prefill on the requester."""
        import uuid as _uuid

        body = await request.json()
        target = str(body.get("target") or "")
        if not target or target == self.addr:
            return web.json_response(
                {"status": "error", "message": "target required"}, status=400
            )
        keys = body.get("keys")
        if isinstance(keys, str):
            keys = kv_fabric.decode_digest(keys)
        keys = [int(x) for x in (keys or [])]
        top = int(body.get("top") or 0)
        if not keys and top <= 0:
            return web.json_response(
                {"status": "error", "message": "keys or top required"},
                status=400,
            )
        loop = asyncio.get_running_loop()
        sessions = await loop.run_in_executor(
            None,
            lambda: self.engine.export_fabric_blocks(
                keys=keys or None, top=top
            ),
        )
        served = 0
        nbytes = 0
        xid_base = str(body.get("xid") or f"fab-{_uuid.uuid4().hex[:12]}")
        for i, sess in enumerate(sessions):
            moved = await self._stream_kv(
                target, sess, sess["meta"]["rid"], f"{xid_base}-{i}"
            )
            if moved is not None:
                served += 1
                nbytes += moved["bytes"]
        self._fabric_stats["serve_sessions"] += served
        self._fabric_stats["serve_bytes"] += nbytes
        return web.json_response(
            {
                "status": "ok",
                "resolved": len(sessions),
                "sessions": served,
                "bytes": nbytes,
            }
        )

    async def _fabric_prefetch(self, hint: dict[str, Any]) -> None:
        """Act on a router hint ({"peer": addr, "keys": digest}) BEFORE
        the engine sees the request: pull the matching block runs from
        the peer so admission finds them in the host tier. Concurrent
        requests carrying the same hint await one fetch (event-loop
        dedup). Every failure degrades to a local prefill — the stream
        stays bit-identical, it just pays the prefill the fabric would
        have skipped."""
        from areal_tpu.utils.http import arequest_with_retry

        peer = str(hint.get("peer") or "")
        keys = hint.get("keys")
        if not peer or not keys or peer == self.addr:
            return
        dedup = keys if isinstance(keys, str) else ",".join(map(str, keys))
        fut = self._fabric_inflight.get(dedup)
        if fut is not None:
            try:
                await asyncio.shield(fut)
            except Exception as e:  # noqa: BLE001 — the original logs it
                logger.debug(f"awaited in-flight fabric fetch failed: {e!r}")
            return
        fut = asyncio.get_running_loop().create_future()
        # no await between the get above and this claim: loop-atomic
        self._fabric_inflight[dedup] = fut
        self._fabric_stats["fetch_attempts"] += 1
        try:
            out = await arequest_with_retry(
                peer,
                "/kv_fetch",
                payload={"keys": keys, "target": self.addr},
                max_retries=1,
                timeout=float(
                    getattr(self.config, "kv_fabric_fetch_timeout_s", 30.0)
                ),
            )
            self._fabric_stats["fetch_sessions"] += int(
                out.get("sessions") or 0
            )
            self._fabric_stats["fetch_bytes"] += int(out.get("bytes") or 0)
            fut.set_result(out)
        except Exception as e:  # noqa: BLE001 — degrade, never wedge
            self._fabric_stats["fetch_failures"] += 1
            logger.warning(
                f"fabric prefetch from {peer} failed ({e!r}); "
                "degrading to local prefill"
            )
            fut.set_result(None)
        finally:
            self._fabric_inflight.pop(dedup, None)

    async def _warm_start(self, request: web.Request) -> web.Response:
        """Cold-start warm-up: ask each peer to push its longest resident
        block runs here before this replica takes traffic. Best-effort —
        a peer that cannot serve simply contributes nothing."""
        from areal_tpu.utils.http import arequest_with_retry

        body = await request.json()
        peers = [
            p for p in body.get("peers") or [] if p and p != self.addr
        ]
        k = int(body.get("max_sessions") or 4)
        if not peers or k <= 0:
            return web.json_response(
                {"status": "error", "message": "peers required"}, status=400
            )
        sessions = nbytes = failures = 0
        for peer in peers:
            try:
                out = await arequest_with_retry(
                    peer,
                    "/kv_fetch",
                    payload={"top": k, "target": self.addr},
                    max_retries=1,
                    timeout=float(
                        getattr(self.config, "kv_fabric_fetch_timeout_s", 30.0)
                    ),
                )
                sessions += int(out.get("sessions") or 0)
                nbytes += int(out.get("bytes") or 0)
            except Exception as e:  # noqa: BLE001 — best-effort warm-up
                failures += 1
                logger.warning(f"warm start from {peer} failed: {e!r}")
        self._fabric_stats["warm_start_sessions"] += sessions
        self._fabric_stats["warm_start_bytes"] += nbytes
        return web.json_response(
            {
                "status": "ok",
                "peers": len(peers),
                "sessions": sessions,
                "bytes": nbytes,
                "failures": failures,
            }
        )

    async def _prefill(self, request: web.Request) -> web.Response:
        """Prefill-only generation (the prefill role's hot path): run the
        prompt, park the KV, optionally hand the session to a decode
        replica. Idempotent per xid like /generate."""
        body = await request.json()
        xid = body.get("xid")
        await fault_injection.afire(
            "server.prefill",
            rid=str(body.get("rid") or ""), xid=str(xid or ""),
            addr=str(self.addr or ""),
        )
        if xid is not None:
            ent = self._idem.get(xid)
            if ent is not None:
                self._idem_hits += 1
                if ent["done"]:
                    self._idem.move_to_end(xid)
                    return web.json_response(
                        {**ent["resp"], "dedup": "completed"}
                    )
                out = await asyncio.shield(ent["fut"])
                return web.json_response({**out, "dedup": "in_progress"})
            ent = {
                "done": False,
                "fut": asyncio.get_running_loop().create_future(),
                "t": time.monotonic(),
            }
            self._idem[xid] = ent
        req = ModelRequest(
            rid=body.get("rid") or ModelRequest().rid,
            input_ids=[int(t) for t in body["input_ids"]],
            gconfig=_parse_gconfig(body.get("gconfig", {})),
            image_data=body.get("image_data"),
        )
        target = body.get("target")
        try:
            resp = await self.engine.aprefill(req)
            out: dict[str, Any] = {
                "status": "ok",
                "stop_reason": resp.stop_reason,
                "latency": resp.latency,
                "migrated": False,
                "kv_bytes": 0,
            }
            if target and target != self.addr:
                moved = await self._migrate_session_out(
                    target, req.rid, xid or f"pf-{req.rid}"
                )
                if moved is not None:
                    out["migrated"] = True
                    out["kv_bytes"] = moved["bytes"]
                    out["transfer_secs"] = moved["secs"]
        except BaseException as e:
            if xid is not None and self._idem.get(xid) is ent:
                del self._idem[xid]
                if not ent["fut"].done():
                    ent["fut"].set_exception(e)
                    ent["fut"].exception()
            raise
        if xid is not None and self._idem.get(xid) is ent:
            self._idem[xid] = {"done": True, "resp": out, "t": time.monotonic()}
            self._idem.move_to_end(xid)
            if not ent["fut"].done():
                ent["fut"].set_result(out)
            self._prune_idem()
        return web.json_response(out)

    async def _kv_recv(self, request: web.Request) -> web.Response:
        """Stage one inbound KV frame under its migration xid."""
        payload = await request.read()
        xid = request.query.get("xid") or ""
        if not xid:
            return web.json_response(
                {"status": "error", "message": "xid required"}, status=400
            )
        # recv seam: an abort models the receiver dying with the frame in
        # hand; torn truncates it in flight — the manifest length-check
        # rejects the torn frame (500) and the sender's frame retry
        # re-covers the byte ranges
        await fault_injection.afire(
            "kv.migrate.recv", xid=xid, addr=str(self.addr or "")
        )
        payload = fault_injection.tear("kv.migrate.recv", payload, xid=xid)
        if xid in self._kv_done:
            # straggler frame of an already-committed migration (the
            # sender replayed after losing the commit response): drop it,
            # the commit retry will hit the dedup cache
            return web.json_response({"status": "ok", "staged": 0})
        ent = self._kv_staging.get(xid)
        if ent is None:
            from areal_tpu.core.weight_transfer import WeightStaging

            ent = {"staging": WeightStaging(), "t0": time.monotonic()}
            self._kv_staging[xid] = ent
        ent["last_t"] = time.monotonic()
        ent["staging"].add_bucket(payload)  # torn frame -> ValueError -> 500
        self._migrate_stats["in_frames"] += 1
        self._prune_kv_maps()
        return web.json_response(
            {"status": "ok", "staged": len(ent["staging"])}
        )

    async def _kv_commit(self, request: web.Request) -> web.Response:
        """Finalize + import a staged migration; idempotent per xid."""
        body = await request.json()
        xid = str(body.get("xid") or "")
        done = self._kv_done.get(xid)
        if done is not None:
            # the sender lost our response and replayed: never import twice
            self._kv_done.move_to_end(xid)
            self._migrate_stats["commit_dedups"] += 1
            return web.json_response({**done["resp"], "dedup": True})
        ent = self._kv_staging.get(xid)
        if ent is None:
            return web.json_response(
                {"status": "error", "message": f"no staged kv for {xid!r}"},
                status=400,
            )
        from areal_tpu.core.weight_transfer import unpack_kv_sessions

        try:
            sessions = unpack_kv_sessions(ent["staging"].finalize())
            if not sessions:
                raise ValueError("no complete kv session staged")
        except (RuntimeError, ValueError) as e:
            # incomplete/malformed/empty: KEEP the staging so the
            # sender's replay can top up the missing byte ranges and
            # re-commit
            return web.json_response(
                {"status": "error", "message": str(e)}, status=400
            )
        del self._kv_staging[xid]
        loop = asyncio.get_running_loop()
        t0 = time.monotonic()
        counts = {
            "ok": 0, "stale_version": 0, "kv_dtype_mismatch": 0, "rejected": 0,
        }
        rids = []
        for meta, k, v, scales in sessions:
            ks, vs = scales if scales is not None else (None, None)
            verdict = await loop.run_in_executor(
                None, self.engine.import_session, meta, k, v, ks, vs
            )
            counts[verdict] = counts.get(verdict, 0) + 1
            if verdict == "ok":
                rids.append(meta["rid"])
        resp = {
            "status": "ok",
            "imported": counts["ok"],
            "stale_version": counts["stale_version"],
            "kv_dtype_mismatch": counts["kv_dtype_mismatch"],
            "rejected": counts["rejected"],
            "rids": rids,
        }
        self._kv_done[xid] = {"resp": resp, "t": time.monotonic()}
        self._migrate_stats["in_commits"] += 1
        self._migrate_stats["transfer_secs"] += time.monotonic() - t0
        self._prune_kv_maps()
        return web.json_response(resp)

    async def _drain(self, request: web.Request) -> web.Response:
        """Stream every resumable session to the target replicas (scale-
        down / maintenance): in-flight generations are parked first (their
        clients resume through the interrupt loop and the router lands
        them on a survivor, where the migrated KV makes the resume a
        zero-re-prefill promotion).

        Drains are serialized per server: a /drain arriving while one is
        already running (a supervisor retry racing an operator) awaits the
        in-flight drain and REPLAYS its result instead of exporting the
        same sessions twice — each concurrent export would mint fresh
        drain-xids, so without this guard the idempotency tables on the
        targets could not dedup the double import."""
        body = await request.json()
        targets = [t for t in body.get("targets") or [] if t and t != self.addr]
        if not targets:
            return web.json_response(
                {"status": "error", "message": "targets required"}, status=400
            )
        if (
            self._drain_inflight is not None
            and not self._drain_inflight.done()
        ):
            # shield: a duplicate whose client gives up must not cancel
            # the original drain mid-export
            resp = await asyncio.shield(self._drain_inflight)
            return web.json_response(dict(resp, dedup="in_progress"))
        fut = asyncio.get_running_loop().create_future()
        # no await between the done-check above and this assignment: the
        # check-and-claim is atomic on the one event loop
        self._drain_inflight = fut
        try:
            resp = await self._drain_once(body, targets)
            status = 200
        except Exception as e:  # noqa: BLE001 — waiters need a result,
            # not a never-retrieved exception
            resp = {"status": "error", "message": repr(e)}
            status = 500
        fut.set_result(resp)
        return web.json_response(resp, status=status)

    async def _drain_once(
        self, body: dict[str, Any], targets: list[str]
    ) -> dict[str, Any]:
        import uuid as _uuid

        loop = asyncio.get_running_loop()
        async with self._ctl_lock:
            await loop.run_in_executor(None, self.engine.pause_generation)
            aborted = (
                self.engine.abort_all()
                if body.get("abort_active", True)
                else 0
            )
            if not self._client_paused:
                self.engine.continue_generation()
        # fleet fabric cheap drain: blocks the survivors can re-fetch by
        # content key travel as a single meta-only frame (identity, not
        # kilobytes of KV) — the supervisor passes the union of survivor
        # digests as `refetchable`
        refetchable: set[int] | None = None
        rf = body.get("refetchable")
        if rf is not None and getattr(self.config, "kv_fabric", True):
            if isinstance(rf, str):
                rf = kv_fabric.decode_digest(rf)
            refetchable = {int(x) for x in rf}
        rids = self.engine.list_exportable_sessions()
        drained = failed = meta_only = 0
        total_bytes = 0
        # kwarg only when a digest was supplied: plain drains keep the
        # pre-fabric `_migrate_session_out(target, rid, xid)` call shape
        # (overridable seam — see tests/test_fleet.py's slow_migrate)
        kw = {} if refetchable is None else {"refetchable": refetchable}
        for i, rid in enumerate(rids):
            xid = f"drain-{_uuid.uuid4().hex[:12]}"
            moved = await self._migrate_session_out(
                targets[i % len(targets)], rid, xid, **kw
            )
            if moved is None:
                failed += 1
            else:
                drained += 1
                total_bytes += moved["bytes"]
                if moved.get("meta_only"):
                    meta_only += 1
        return {
            "status": "ok",
            "aborted": aborted,
            "sessions": len(rids),
            "drained": drained,
            "failed": failed,
            "meta_only": meta_only,
            "bytes": total_bytes,
        }

    async def _set_role(self, request: web.Request) -> web.Response:
        """Flip this replica's role (the supervisor's re-role transition,
        issued only after a committed /drain). The role only steers the
        router's scheduler — every replica serves every endpoint — so the
        flip is a config write here plus the next /health poll on the
        router side."""
        body = await request.json()
        role = str(body.get("role", "")).lower()
        if role not in ("unified", "prefill", "decode"):
            return web.json_response(
                {"status": "error", "message": f"bad role {role!r}"},
                status=400,
            )
        old = getattr(self.config, "role", "unified")
        self.config.role = role
        logger.info(f"role flipped {old} -> {role}")
        return web.json_response(
            {"status": "ok", "old_role": old, "role": role}
        )

    # -- lifecycle ------------------------------------------------------
    def build_app(self) -> web.Application:
        app = web.Application(client_max_size=1024**3)
        app.router.add_get("/health", self._health)
        app.router.add_get("/info", self._info)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_post("/generate", self._generate)
        app.router.add_post("/pause_generation", self._pause)
        app.router.add_post("/continue_generation", self._continue)
        app.router.add_post(
            "/update_weights_from_disk", self._update_weights_from_disk
        )
        app.router.add_post(
            "/update_weights_from_tensor", self._update_weights_from_tensor
        )
        app.router.add_post("/commit_weights", self._commit_weights)
        app.router.add_post("/abort_weights", self._abort_weights)
        app.router.add_post("/set_version", self._set_version)
        app.router.add_post("/prefill", self._prefill)
        app.router.add_post("/kv_recv", self._kv_recv)
        app.router.add_post("/kv_commit", self._kv_commit)
        app.router.add_post("/kv_fetch", self._kv_fetch)
        app.router.add_post("/warm_start", self._warm_start)
        app.router.add_post("/drain", self._drain)
        app.router.add_post("/set_role", self._set_role)
        return app

    async def start(
        self,
        host: str = "0.0.0.0",
        port: int = 0,
        prewarm: dict[str, Any] | None = None,
    ) -> str:
        """Initialize the engine, optionally prewarm, THEN bind the HTTP
        listener. `prewarm` (kwargs for `engine.prewarm`) must run before
        the port exists: once the listener is up, a /generate or /pause
        arriving mid-warmup would make the wave sizes nondeterministic
        (or trip prewarm's external-pause guard and kill startup)."""
        if self._owns_engine:
            self.engine.initialize()
        if prewarm is not None:
            await asyncio.get_running_loop().run_in_executor(
                None, lambda: self.engine.prewarm(**prewarm)
            )
        self._runner = web.AppRunner(
            self.build_app(), shutdown_timeout=self.shutdown_grace
        )
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        actual_port = self._runner.addresses[0][1]
        ip = _local_ip() if host in ("0.0.0.0", "::") else host
        self.addr = f"{ip}:{actual_port}"
        logger.info(f"decode server listening on {self.addr}")
        return self.addr

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
        if self._owns_engine:
            self.engine.destroy()

    def register(self, experiment_name: str, trial_name: str, server_id: str):
        assert self.addr is not None
        name_resolve.add(
            names.gen_server(experiment_name, trial_name, server_id),
            self.addr,
            keepalive_ttl=None,
            replace=True,
        )


def _local_ip() -> str:
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 80))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"


async def _serve(args: argparse.Namespace) -> None:
    config = JaxDecodeConfig(
        model_path=args.model_path,
        dtype=args.dtype,
        role=args.role,
        kv_migrate_chunk_mb=args.kv_migrate_chunk_mb,
        kv_import_pool_mb=args.kv_import_pool_mb,
        context_length=args.context_length,
        max_running_requests=args.max_running_requests,
        new_tokens_per_chunk=args.new_tokens_per_chunk,
        decode_runahead_chunks=args.decode_runahead_chunks,
        kv_layout=args.kv_layout,
        kv_dtype=args.kv_dtype,
        weight_dtype=args.weight_dtype,
        kv_host_pool_mb=args.kv_host_pool_mb,
        paged_attn_impl=args.paged_attn_impl,
        spec_decode=args.spec_decode,
        spec_k=args.spec_k,
        spec_ngram_max=args.spec_ngram_max,
        random_seed=args.seed,
        tensor_parallel_size=args.tensor_parallel_size,
    )
    tokenizer = None
    if args.model_path and not args.skip_tokenizer_init and not args.scratch_model:
        try:
            from transformers import AutoTokenizer

            tokenizer = AutoTokenizer.from_pretrained(args.model_path)
        except Exception as e:  # noqa: BLE001
            logger.warning(f"tokenizer load failed ({e}); stop-on-eos disabled")
    server = DecodeServer(config, tokenizer=tokenizer)
    if args.scratch_model:
        # Offline smoke mode: serve a from-scratch tiny model described by a
        # JSON ModelConfig dict — lets launcher E2E tests (and air-gapped
        # demo runs) exercise the full DECOUPLED path without HF downloads.
        import json as _json

        import jax as _jax

        from areal_tpu.models.qwen2 import ModelConfig, init_params

        mc = ModelConfig(
            **{
                **_json.loads(args.scratch_model),
                "dtype": args.dtype,
                "param_dtype": args.dtype,
            }
        )
        server.engine.set_model(init_params(mc, _jax.random.PRNGKey(args.seed)), mc)
    # Deterministic jit warmup BEFORE the HTTP listener binds (and so also
    # before registering with the router): live traffic must never pay a
    # first-compile (see JaxDecodeEngine.prewarm — which batched-prefill
    # variant traffic compiles is arrival-timing dependent, so
    # serving-warmed engines still hit compile stalls), and a request or
    # /pause arriving mid-warmup would break wave determinism or trip
    # prewarm's external-pause guard.
    prewarm = (
        dict(
            prompt_len=args.prewarm_prompt_len,
            new_tokens=args.prewarm_new_tokens,
        )
        if args.prewarm_prompt_len > 0
        else None
    )
    await server.start(args.host, args.port, prewarm=prewarm)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    if args.experiment_name and args.trial_name:
        server.register(
            args.experiment_name, args.trial_name, args.server_id or server.addr
        )
        # Self-terminate when the trainer broadcasts a terminal status —
        # servers must not linger after the experiment ends (reference:
        # ExpStatus watch, realhf master_worker.py:485-495).
        from areal_tpu.utils.experiment import watch_until_terminal

        watch_until_terminal(
            args.experiment_name,
            args.trial_name,
            lambda status: loop.call_soon_threadsafe(stop.set),
        )
    try:
        await stop.wait()
    finally:
        await server.stop()


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description="areal_tpu decode server")
    p.add_argument("--model-path", default="")
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument(
        "--role",
        default="unified",
        choices=["unified", "prefill", "decode"],
        help="disaggregated fleet role: 'prefill' replicas run prompt "
             "prefills (/prefill) and stream the KV to decode replicas "
             "over the bucketed KV wire; 'decode' replicas import those "
             "sessions and resume them with zero re-prefill; 'unified' "
             "(default) does both. Roles steer the router — every role "
             "still serves every endpoint, so a degraded fleet keeps "
             "working",
    )
    p.add_argument(
        "--kv-migrate-chunk-mb",
        type=float,
        default=64.0,
        help="frame size (MiB per HTTP body) for migrated KV sessions",
    )
    p.add_argument(
        "--kv-import-pool-mb",
        type=float,
        default=256.0,
        help="host-tier budget (MiB) created lazily when a migration "
             "arrives while --kv-host-pool-mb is 0 — imported sessions "
             "need a host tier to land in",
    )
    p.add_argument("--context-length", type=int, default=32768)
    p.add_argument("--max-running-requests", type=int, default=64)
    p.add_argument("--new-tokens-per-chunk", type=int, default=128)
    p.add_argument(
        "--decode-runahead-chunks",
        type=int,
        default=1,
        help="chunks the scheduler keeps dispatched on the device while "
             "the host post-processes the previous one (0 = legacy "
             "synchronous loop; output is bit-identical either way)",
    )
    p.add_argument(
        "--kv-layout",
        default="paged",
        choices=["paged", "workspace"],
        help="decode KV access: 'paged' attends in place over the paged "
             "pool through the block table (no per-chunk gather/scatter); "
             "'workspace' is the legacy copy-in/copy-out numerics oracle",
    )
    p.add_argument(
        "--kv-dtype",
        default="fp",
        choices=["fp", "int8"],
        help="paged-pool storage: 'fp' keeps kv_cache_dtype (the numerics "
             "oracle); 'int8' stores the pool quantized with per-row/"
             "per-head scales (needs --kv-layout paged) — ~2x the resident "
             "sessions per MB, and swaps/migration ship the quantized "
             "bytes as-is (mixed-dtype fleets reject imports as honest "
             "misses). Drift is measured (bench.py --mode kvquant), not "
             "assumed zero",
    )
    p.add_argument(
        "--weight-dtype",
        default="fp",
        choices=["fp", "int8"],
        help="serving dtype of the dense matmul kernels: 'fp' serves "
             "--dtype verbatim (the numerics oracle); 'int8' serves "
             "per-output-channel absmax int8 + f32 scales — weight HBM and "
             "push wire bytes ~halve, decode runs the fused dequant-matmul "
             "(Pallas on TPU). The trainer's WeightUpdateMeta.weight_dtype "
             "must match: quantized kernels travel as '.../q' + "
             "'.../scale' wire leaves. Drift is measured (bench.py --mode "
             "wquant), not assumed zero",
    )
    p.add_argument(
        "--kv-host-pool-mb",
        type=float,
        default=0.0,
        help="host-RAM KV tier budget in MiB (0 disables): eviction "
             "offloads parked/preempted slots' KV blocks to pinned host "
             "memory and a resume swaps them back asynchronously instead "
             "of re-prefilling — kv_pool_tokens becomes a working-set "
             "knob, not a capacity wall",
    )
    p.add_argument(
        "--paged-attn-impl",
        default="auto",
        choices=["auto", "pallas", "xla"],
        help="kernel for the in-pool attention read: 'pallas' (TPU "
             "split-KV flash-decode; needs page_size %% 128 == 0), 'xla' "
             "(gather-per-block fallback), 'auto' picks per backend",
    )
    p.add_argument(
        "--spec-decode",
        default="off",
        choices=["off", "ngram"],
        help="draft-free speculative decoding: 'ngram' drafts from each "
             "request's own context (prompt lookup) and verifies all "
             "draft positions in one chunk — token streams and logprobs "
             "stay bit-identical to 'off'",
    )
    p.add_argument(
        "--spec-k",
        type=int,
        default=4,
        help="max draft tokens proposed (and verified) per chunk per slot",
    )
    p.add_argument(
        "--spec-ngram-max",
        type=int,
        default=3,
        help="longest trailing n-gram matched against the request's own "
             "earlier context when drafting",
    )
    p.add_argument(
        "--tp-size",
        dest="tensor_parallel_size",
        type=int,
        default=1,
        help="gen-side tensor parallelism (alloc grammar's server t dim)",
    )
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=int(os.environ.get("PORT", 0)))
    p.add_argument("--experiment-name", default=os.environ.get("AREAL_EXPERIMENT_NAME", ""))
    p.add_argument("--trial-name", default=os.environ.get("AREAL_TRIAL_NAME", ""))
    # knob: launcher-only — discovery identity, not a JaxDecodeConfig mirror
    p.add_argument("--server-id", default="")
    p.add_argument("--skip-tokenizer-init", action="store_true")
    # knob: launcher-only — smoke/E2E harness switch, not a config mirror
    p.add_argument(
        "--scratch-model",
        default="",
        help="JSON ModelConfig dict: serve a from-scratch tiny model "
             "(offline smoke / launcher E2E) instead of loading --model-path",
    )
    # knob: launcher-only — boot-time compile hint, not a config mirror
    p.add_argument(
        "--prewarm-prompt-len",
        type=int,
        default=0,
        help="if >0, deterministically compile the hot decode-path jit "
             "variants at this prompt length before registering with the "
             "router (JaxDecodeEngine.prewarm); production servers should "
             "set this to their typical prompt length",
    )
    # knob: launcher-only — boot-time compile hint, not a config mirror
    p.add_argument(
        "--prewarm-new-tokens",
        type=int,
        default=1,
        help="generation length of the prewarm requests (raise to the "
             "typical response length to also compile the decode chunk at "
             "every KV bucket the context growth reaches)",
    )
    args = p.parse_args(argv)
    # join the experiment's shared discovery store (launcher-provided env)
    name_resolve.reconfigure_from_env()
    asyncio.run(_serve(args))


if __name__ == "__main__":
    main()
