"""Ray launcher: decode-server + trainer actors over a Ray cluster.

Parity: areal/launcher/ray.py:68 RayLauncher — submit_array with PACK
placement groups per node, env hooks wiring distributed env vars, remote
function wrappers around the entrypoint.

TPU notes: Ray schedules by the "TPU" custom resource; each trainer task is
one JAX process owning the host's chips. Import of ray is deferred and
gated — environments without ray get a clear error only when actually
launching.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable

from areal_tpu.utils import logging
from areal_tpu.utils.network import gethostip

logger = logging.getLogger("ray_launcher")

PLACEMENT_GROUP_READY_TIMEOUT = 30.0  # seconds


@dataclasses.dataclass
class PlacementPlan:
    """Pure description of a PACK placement for a task array (parity:
    the per-node bundles of areal/launcher/ray.py:172-206): one bundle
    per node holding that node's aggregate CPU/TPU/memory, plus the
    bundle index each task rank schedules into. Building the plan is
    side-effect-free so it unit-tests without a cluster."""

    bundles: list[dict[str, float]]
    strategy: str
    bundle_index: list[int]  # per task rank

    @property
    def nodes(self) -> int:
        return len(self.bundles)


def build_placement_plan(
    count: int,
    nodes: int,
    *,
    tpus_per_task: int = 0,
    cpus_per_task: int = 4,
    mem_mb_per_task: int = 16 * 1024,
) -> PlacementPlan:
    if nodes <= 0 or count % nodes != 0:
        raise ValueError(
            f"count {count} must be a positive multiple of nodes {nodes}"
        )
    tasks_per_node = count // nodes
    bundle: dict[str, float] = {
        "CPU": float(cpus_per_task * tasks_per_node),
        "memory": float(mem_mb_per_task * tasks_per_node * 1024 * 1024),
    }
    if tpus_per_task:
        bundle["TPU"] = float(tpus_per_task * tasks_per_node)
    return PlacementPlan(
        bundles=[dict(bundle) for _ in range(nodes)],
        strategy="PACK",
        bundle_index=[i // tasks_per_node for i in range(count)],
    )


def _require_ray():
    try:
        import ray  # noqa: F401

        return ray
    except ImportError as e:  # pragma: no cover - ray absent in CI image
        raise RuntimeError(
            "RayLauncher requires the `ray` package; install it or use "
            "areal_tpu.launcher.local / slurm"
        ) from e


def resolve_coordinator(
    experiment_name: str,
    trial_name: str,
    rank: int,
    *,
    group: str = "ray_coord",
    timeout: float = 300.0,
) -> str:
    """jax.distributed rendezvous address, decided *inside* the tasks.

    The driver cannot know where Ray will place rank 0, so rank 0 binds a
    free port on whatever node it landed on and publishes host:port through
    name_resolve (which must be a cross-host backend — nfs/etcd); other
    ranks block on the key. `group` must be unique per submit_array so
    concurrent arrays (and restarted trials, see clear below) don't read
    each other's coordinator.
    """
    from areal_tpu.utils import name_resolve, names
    from areal_tpu.utils.network import find_free_ports

    key = names.distributed_peer(experiment_name, trial_name, group, 0)
    if rank == 0:
        addr = f"{gethostip()}:{find_free_ports(1)[0]}"
        name_resolve.add(key, addr, replace=True)
        return addr
    return name_resolve.wait(key, timeout=timeout)


def clear_coordinator(experiment_name: str, trial_name: str, group: str) -> None:
    from areal_tpu.utils import name_resolve, names

    try:
        name_resolve.delete(
            names.distributed_peer(experiment_name, trial_name, group, 0)
        )
    except Exception as e:  # noqa: BLE001 — nothing to clear
        logger.debug(f"coordinator clear skipped: {e!r}")


def trainer_env_hook(rank: int, world: int, coordinator: str) -> dict[str, str]:
    """Env for one trainer process (jax.distributed rendezvous)."""
    return {
        "AREAL_TPU_NUM_PROCESSES": str(world),
        "AREAL_TPU_PROCESS_ID": str(rank),
        "AREAL_TPU_COORDINATOR": coordinator,
    }


def _dist_task_wrapper(
    fn: Callable, experiment_name: str, trial_name: str, group: str
):
    """Wrap the user fn so each task resolves the coordinator at runtime and
    exports the distributed env before user code imports jax."""

    def task(rank: int, world: int, *args):
        coord = resolve_coordinator(
            experiment_name, trial_name, rank, group=group
        )
        os.environ.update(trainer_env_hook(rank, world, coord))
        return fn(rank, *args)

    return task


class RayLauncher:
    def __init__(self, experiment_name: str, trial_name: str):
        self.experiment_name = experiment_name
        self.trial_name = trial_name
        self.refs: dict[str, Any] = {}
        # PGs cached per array name: a recover-restart of the same trial
        # reuses the reserved nodes instead of re-queueing behind other
        # jobs (parity: ray.py:205 "Reuse placement group in recover runs").
        self.placement_groups: dict[str, Any] = {}

    def _ensure_placement_group(self, name: str, plan: PlacementPlan):
        """Reserve (or reuse) the PACK placement group for an array.

        Reuse requires the SAME plan — a resubmit with a new topology
        (scale-up, recover onto different node counts) releases the old
        reservation instead of scheduling ranks into out-of-range or
        undersized bundles."""
        ray = _require_ray()
        plan_key = (
            plan.strategy,
            tuple(tuple(sorted(b.items())) for b in plan.bundles),
        )
        cached = self.placement_groups.get(name)
        if cached is not None:
            cached_key, pg = cached
            if cached_key == plan_key:
                return pg
            try:
                ray.util.remove_placement_group(pg)
            except Exception as e:  # noqa: BLE001 — already gone
                logger.debug(f"stale placement group removal: {e!r}")
            del self.placement_groups[name]
        pg = ray.util.placement_group(
            bundles=plan.bundles, strategy=plan.strategy
        )
        try:
            ray.get(pg.ready(), timeout=PLACEMENT_GROUP_READY_TIMEOUT)
        except Exception:
            logger.error(
                "placement group not ready: the experiment's resource "
                f"demand ({plan.nodes} nodes x {plan.bundles[0]}) likely "
                f"exceeds the cluster; ray.nodes(): {ray.nodes()}"
            )
            # a pending PG holds its queue position forever; release it so
            # retries (and other jobs) aren't starved by our own orphans
            try:
                ray.util.remove_placement_group(pg)
            except Exception as e:  # noqa: BLE001 — best-effort cleanup
                logger.debug(f"orphan placement group removal: {e!r}")
            raise
        self.placement_groups[name] = (plan_key, pg)
        return pg

    def submit_array(
        self,
        name: str,
        fn: Callable,
        count: int,
        *,
        nodes: int | None = None,
        tpus_per_task: int = 0,
        cpus_per_task: int = 4,
        mem_mb_per_task: int = 16 * 1024,
        env_hook: Callable[[int], dict[str, str]] | None = None,
        args: tuple = (),
    ) -> list[Any]:
        """Run `fn(rank, *args)` as `count` Ray tasks.

        With `nodes` set, tasks are PACKed via a placement group: each
        node's tasks land in that node's bundle (bundle_index =
        rank // tasks_per_node), so a multi-host trainer's ranks are
        physically adjacent and ICI/DCN topology assumptions hold. With
        `nodes=None` (default) Ray schedules by plain per-task resource
        requests — callers who don't know the cluster shape must not be
        forced into a single-node bundle that can never become ready."""
        ray = _require_ray()
        if not ray.is_initialized():  # pragma: no cover - needs cluster
            ray.init(address=os.environ.get("RAY_ADDRESS", "auto"))

        pg = None
        plan = None
        if nodes is not None:
            from ray.util.scheduling_strategies import (
                PlacementGroupSchedulingStrategy,
            )

            plan = build_placement_plan(
                count,
                nodes,
                tpus_per_task=tpus_per_task,
                cpus_per_task=cpus_per_task,
                mem_mb_per_task=mem_mb_per_task,
            )
            pg = self._ensure_placement_group(name, plan)
        resources = {"TPU": tpus_per_task} if tpus_per_task else None
        group = f"ray_coord/{name}"
        # Drop any stale coordinator key from a previous run of this trial
        # before ranks start racing on it.
        clear_coordinator(self.experiment_name, self.trial_name, group)
        task = _dist_task_wrapper(
            fn, self.experiment_name, self.trial_name, group
        )

        refs = []
        for rank in range(count):
            env = dict(env_hook(rank)) if env_hook is not None else {}
            opts: dict[str, Any] = dict(
                num_cpus=cpus_per_task,
                memory=mem_mb_per_task * 1024 * 1024,
                resources=resources,
                runtime_env={"env_vars": env} if env else None,
            )
            if pg is not None:
                opts["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                    placement_group=pg,
                    placement_group_bundle_index=plan.bundle_index[rank],
                    placement_group_capture_child_tasks=True,
                )
            remote_fn = ray.remote(**opts)(task)
            refs.append(remote_fn.remote(rank, count, *args))
        self.refs[name] = refs
        logger.info(
            f"submitted ray array {name} x{count}"
            + (f" over {nodes} node bundles" if pg is not None else "")
        )
        return refs

    def wait(self) -> None:
        ray = _require_ray()
        for name, refs in self.refs.items():
            ray.get(refs)

    def stop_all(self) -> None:
        try:
            ray = _require_ray()
        except RuntimeError:
            return
        for refs in self.refs.values():
            for r in refs:
                ray.cancel(r, force=True)
        self.refs.clear()
        for _, pg in self.placement_groups.values():
            try:
                ray.util.remove_placement_group(pg)
            except Exception as e:  # noqa: BLE001 — already gone
                logger.debug(f"placement group removal on stop: {e!r}")
        self.placement_groups.clear()
