"""Ray launcher: decode-server + trainer actors over a Ray cluster.

Parity: areal/launcher/ray.py:68 RayLauncher — submit_array with PACK
placement groups per node, env hooks wiring distributed env vars, remote
function wrappers around the entrypoint.

TPU notes: Ray schedules by the "TPU" custom resource; each trainer task is
one JAX process owning the host's chips. Import of ray is deferred and
gated — environments without ray get a clear error only when actually
launching.
"""

from __future__ import annotations

import os
from typing import Any, Callable

from areal_tpu.utils import logging
from areal_tpu.utils.network import gethostip

logger = logging.getLogger("ray_launcher")


def _require_ray():
    try:
        import ray  # noqa: F401

        return ray
    except ImportError as e:  # pragma: no cover - ray absent in CI image
        raise RuntimeError(
            "RayLauncher requires the `ray` package; install it or use "
            "areal_tpu.launcher.local / slurm"
        ) from e


def resolve_coordinator(
    experiment_name: str,
    trial_name: str,
    rank: int,
    *,
    group: str = "ray_coord",
    timeout: float = 300.0,
) -> str:
    """jax.distributed rendezvous address, decided *inside* the tasks.

    The driver cannot know where Ray will place rank 0, so rank 0 binds a
    free port on whatever node it landed on and publishes host:port through
    name_resolve (which must be a cross-host backend — nfs/etcd); other
    ranks block on the key. `group` must be unique per submit_array so
    concurrent arrays (and restarted trials, see clear below) don't read
    each other's coordinator.
    """
    from areal_tpu.utils import name_resolve, names
    from areal_tpu.utils.network import find_free_ports

    key = names.distributed_peer(experiment_name, trial_name, group, 0)
    if rank == 0:
        addr = f"{gethostip()}:{find_free_ports(1)[0]}"
        name_resolve.add(key, addr, replace=True)
        return addr
    return name_resolve.wait(key, timeout=timeout)


def clear_coordinator(experiment_name: str, trial_name: str, group: str) -> None:
    from areal_tpu.utils import name_resolve, names

    try:
        name_resolve.delete(
            names.distributed_peer(experiment_name, trial_name, group, 0)
        )
    except Exception:
        pass


def trainer_env_hook(rank: int, world: int, coordinator: str) -> dict[str, str]:
    """Env for one trainer process (jax.distributed rendezvous)."""
    return {
        "AREAL_TPU_NUM_PROCESSES": str(world),
        "AREAL_TPU_PROCESS_ID": str(rank),
        "AREAL_TPU_COORDINATOR": coordinator,
    }


def _dist_task_wrapper(
    fn: Callable, experiment_name: str, trial_name: str, group: str
):
    """Wrap the user fn so each task resolves the coordinator at runtime and
    exports the distributed env before user code imports jax."""

    def task(rank: int, world: int, *args):
        coord = resolve_coordinator(
            experiment_name, trial_name, rank, group=group
        )
        os.environ.update(trainer_env_hook(rank, world, coord))
        return fn(rank, *args)

    return task


class RayLauncher:
    def __init__(self, experiment_name: str, trial_name: str):
        self.experiment_name = experiment_name
        self.trial_name = trial_name
        self.refs: dict[str, Any] = {}

    def submit_array(
        self,
        name: str,
        fn: Callable,
        count: int,
        *,
        tpus_per_task: int = 0,
        cpus_per_task: int = 4,
        mem_mb_per_task: int = 16 * 1024,
        env_hook: Callable[[int], dict[str, str]] | None = None,
        args: tuple = (),
    ) -> list[Any]:
        """Run `fn(rank, *args)` as `count` Ray tasks, PACKed per node."""
        ray = _require_ray()
        if not ray.is_initialized():  # pragma: no cover - needs cluster
            ray.init(address=os.environ.get("RAY_ADDRESS", "auto"))

        resources = {"TPU": tpus_per_task} if tpus_per_task else None
        group = f"ray_coord/{name}"
        # Drop any stale coordinator key from a previous run of this trial
        # before ranks start racing on it.
        clear_coordinator(self.experiment_name, self.trial_name, group)
        task = _dist_task_wrapper(
            fn, self.experiment_name, self.trial_name, group
        )

        refs = []
        for rank in range(count):
            env = dict(env_hook(rank)) if env_hook is not None else {}
            remote_fn = ray.remote(
                num_cpus=cpus_per_task,
                memory=mem_mb_per_task * 1024 * 1024,
                resources=resources,
                runtime_env={"env_vars": env} if env else None,
            )(task)
            refs.append(remote_fn.remote(rank, count, *args))
        self.refs[name] = refs
        logger.info(f"submitted ray array {name} x{count}")
        return refs

    def wait(self) -> None:
        ray = _require_ray()
        for name, refs in self.refs.items():
            ray.get(refs)

    def stop_all(self) -> None:
        try:
            ray = _require_ray()
        except RuntimeError:
            return
        for refs in self.refs.values():
            for r in refs:
                ray.cancel(r, force=True)
        self.refs.clear()
