"""Types for the OpenAI-compatible client (parity:
areal/experimental/openai/types.py:17 InteractionWithTokenLogpReward).

The client records one `InteractionWithTokenLogpReward` per completion call:
the token-level view (ids, logprobs, weight versions) that RL training needs
but the OpenAI response shape hides. Multi-turn conversations link
interactions via `parent_id` (detected by token-prefix matching), so
turn-discounted credit assignment can flow rewards backward along the chain.
"""

from __future__ import annotations

import dataclasses
from typing import Any


@dataclasses.dataclass
class InteractionWithTokenLogpReward:
    id: str
    messages: list[dict[str, Any]]
    input_tokens: list[int]
    output_tokens: list[int]
    output_logprobs: list[float]
    output_versions: list[int]
    reward: float | None = None
    parent_id: str | None = None

    @property
    def seq(self) -> list[int]:
        return list(self.input_tokens) + list(self.output_tokens)

    def to_training_row(self) -> dict[str, Any]:
        import numpy as np

        seq = self.seq
        il, ol = len(self.input_tokens), len(self.output_tokens)
        return dict(
            input_ids=np.array(seq, dtype=np.int32),
            loss_mask=np.array([0] * il + [1] * ol, dtype=np.int32),
            logprobs=np.array(
                [0.0] * il + list(self.output_logprobs), dtype=np.float32
            ),
            versions=np.array(
                [-1] * il + list(self.output_versions), dtype=np.int32
            ),
            rewards=np.float32(self.reward if self.reward is not None else 0.0),
            begin_of_answer=np.int32(il),
        )


@dataclasses.dataclass
class ChatMessage:
    role: str
    content: str

    def model_dump(self) -> dict[str, str]:
        return {"role": self.role, "content": self.content}


@dataclasses.dataclass
class Choice:
    index: int
    message: ChatMessage
    finish_reason: str


@dataclasses.dataclass
class Usage:
    prompt_tokens: int
    completion_tokens: int

    @property
    def total_tokens(self) -> int:
        return self.prompt_tokens + self.completion_tokens


@dataclasses.dataclass
class ChatCompletion:
    """Minimal OpenAI-shaped chat completion (we do not depend on the
    `openai` package; this mirrors the fields user code reads)."""

    id: str
    choices: list[Choice]
    usage: Usage
    model: str = "areal-tpu"
    object: str = "chat.completion"
