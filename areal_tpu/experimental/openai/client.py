"""OpenAI-compatible client over InferenceEngine.agenerate.

Parity: areal/experimental/openai/client.py:481 ArealOpenAI — agentic user
code written against `client.chat.completions.create(messages=...)` runs
unchanged against our decode servers, while the client records the
token-level interaction (ids/logprobs/versions) each call, supports
`set_reward` / `apply_reward_discount` for turn-discounted credit, and
`export_interactions` emits training rows with multi-turn prefix matching.

Unlike the reference we do not subclass `openai.AsyncOpenAI` (the package
is not a dependency); the response objects mirror the attribute surface
agent code actually touches (choices[0].message.content, id, usage).
"""

from __future__ import annotations

import uuid
from typing import Any

from areal_tpu.api.cli_args import GenerationHyperparameters
from areal_tpu.api.io_struct import ModelRequest
from areal_tpu.experimental.openai.types import (
    ChatCompletion,
    ChatMessage,
    Choice,
    InteractionWithTokenLogpReward,
    Usage,
)
from areal_tpu.utils.data import pad_sequences_to_tensors


class _Completions:
    def __init__(self, client: "ArealOpenAI"):
        self._client = client

    async def create(
        self,
        *,
        messages: list[dict[str, Any]],
        temperature: float | None = None,
        top_p: float | None = None,
        max_tokens: int | None = None,
        max_completion_tokens: int | None = None,
        stop: list[str] | None = None,
        **_ignored: Any,
    ) -> ChatCompletion:
        c = self._client
        gconfig = c.gconfig.new(n_samples=1)
        if temperature is not None:
            gconfig.temperature = temperature
            gconfig.greedy = temperature == 0.0
        if top_p is not None:
            gconfig.top_p = top_p
        limit = max_completion_tokens or max_tokens
        if limit is not None:
            gconfig.max_new_tokens = limit

        input_ids = c.tokenizer.apply_chat_template(
            messages, add_generation_prompt=True, tokenize=True
        )
        resp = await c.engine.agenerate(
            ModelRequest(
                rid=str(uuid.uuid4()),
                input_ids=list(input_ids),
                gconfig=gconfig,
                tokenizer=c.tokenizer,
            )
        )
        text = c.tokenizer.decode(resp.output_tokens)
        cid = f"chatcmpl-{uuid.uuid4().hex}"
        interaction = InteractionWithTokenLogpReward(
            id=cid,
            messages=[dict(m) for m in messages],
            input_tokens=list(resp.input_tokens),
            output_tokens=list(resp.output_tokens),
            output_logprobs=list(resp.output_logprobs),
            output_versions=list(resp.output_versions),
            parent_id=c._match_parent(resp.input_tokens),
        )
        c._interactions[cid] = interaction
        return ChatCompletion(
            id=cid,
            choices=[
                Choice(
                    index=0,
                    message=ChatMessage(role="assistant", content=text),
                    finish_reason=(
                        "stop" if resp.stop_reason == "stop" else "length"
                    ),
                )
            ],
            usage=Usage(
                prompt_tokens=resp.input_len,
                completion_tokens=resp.output_len,
            ),
        )


class _Chat:
    def __init__(self, client: "ArealOpenAI"):
        self.completions = _Completions(client)


class ArealOpenAI:
    def __init__(
        self,
        engine: Any,
        tokenizer: Any,
        gconfig: GenerationHyperparameters | None = None,
    ):
        self.engine = engine
        self.tokenizer = tokenizer
        self.gconfig = gconfig or GenerationHyperparameters()
        self.chat = _Chat(self)
        self._interactions: dict[str, InteractionWithTokenLogpReward] = {}

    # -- reward plumbing ------------------------------------------------
    def get_interaction(self, completion_id: str) -> InteractionWithTokenLogpReward:
        return self._interactions[completion_id]

    def set_reward(self, completion_id: str, reward: float) -> None:
        self._interactions[completion_id].reward = float(reward)

    def _match_parent(self, input_tokens: list[int]) -> str | None:
        """Multi-turn detection: the previous interaction whose full token
        sequence is a strict prefix of this call's prompt (reference
        client.py export_interactions prefix matching). Longest match wins."""
        best, best_len = None, 0
        for other in self._interactions.values():
            seq = other.seq
            n = len(seq)
            if n > best_len and n < len(input_tokens) and input_tokens[:n] == seq:
                best, best_len = other.id, n
        return best

    def apply_reward_discount(self, turn_discount: float = 1.0) -> None:
        """Back-propagate rewards along parent chains: a turn with no
        explicit reward inherits `turn_discount ×` its latest child's
        reward (reference: turn-level discounted credit assignment)."""
        children: dict[str, list[InteractionWithTokenLogpReward]] = {}
        for it in self._interactions.values():
            if it.parent_id is not None:
                children.setdefault(it.parent_id, []).append(it)

        def resolve(it: InteractionWithTokenLogpReward) -> float | None:
            kids = children.get(it.id, [])
            for kid in kids:
                if kid.reward is None:
                    resolve(kid)
            rewards = [k.reward for k in kids if k.reward is not None]
            if it.reward is None and rewards:
                it.reward = turn_discount * max(rewards)
            return it.reward

        for it in self._interactions.values():
            resolve(it)

    def export_interactions(self, style: str = "individual") -> dict[str, Any]:
        """Build one padded training batch from all recorded interactions.

        style="individual": one row per completion (each row's prompt is the
        full conversation prefix, loss on that turn's tokens only) — the
        multi-turn-safe default, matching the reference's per-interaction
        export.
        """
        assert style == "individual", style
        rows = [
            it.to_training_row()
            for it in self._interactions.values()
            if it.reward is not None
        ]
        if not rows:
            raise RuntimeError(
                "no rewarded interactions to export — call set_reward "
                "(and optionally apply_reward_discount) first"
            )
        return pad_sequences_to_tensors(rows)
