from areal_tpu.experimental.openai.client import ArealOpenAI
from areal_tpu.experimental.openai.types import InteractionWithTokenLogpReward

__all__ = ["ArealOpenAI", "InteractionWithTokenLogpReward"]
