"""AR2xx — JAX hot-path hazards (pjit/TPU invariants).

All heuristics are intentionally conservative about what counts as a
"device array": a local name is device-typed only if it was assigned from a
`jnp.*` / `jax.*` call (minus the explicit host transfers) or from a call to
a name known to be jit-wrapped in the same scope/module. Unknown receivers
are NOT flagged — fewer false positives beats exhaustiveness for a tier-1
gate; the fixtures pin the contract.

AR201  implicit host sync inside a `for`/`while` loop: `.item()`,
       `float()`/`int()` on a device array, `np.asarray`/`np.array` of a
       device array. Each of these blocks the host on the device stream —
       inside a decode/train step loop that serializes the pipeline and
       pollutes timing measurements.

AR202  use of a donated buffer after a `donate_argnums`/`donate_argnames`
       jit call: the callee's XLA buffers alias the argument, which is
       deleted after the call. Reads after the call site (without an
       intervening rebind) are use-after-free.

AR203  `jnp.asarray(x)` of a host array `x` that is later mutated in place.
       On CPU (and in unified-memory setups) `jnp.asarray` zero-copies
       aligned numpy buffers, so the later mutation races whatever
       computation the upload feeds (the PR 3 run-ahead bug class). Bare
       names and `self.*` attributes are tracked; wrapping the upload in
       `np.array(...)` (an explicit copy) clears the finding.

AR204  retrace hazard: a loop-varying Python scalar passed to a
       jit-compiled function (each distinct value re-specializes or
       fragments the jit cache), or an unhashable literal (list/dict/set)
       passed at a static arg position (TypeError at runtime).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from areal_tpu.analysis.core import Finding, SourceFile, call_root

_HOST_SYNC_CASTS = {"float", "int"}
_NP_CONVERTERS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_INPLACE_MUTATORS = {"fill", "sort", "reverse", "partition", "put", "setflags"}


@dataclass
class _JitInfo:
    static_argnums: set = field(default_factory=set)
    static_argnames: set = field(default_factory=set)
    donate_argnums: set = field(default_factory=set)
    donate_argnames: set = field(default_factory=set)
    line: int = 0


def walk_scope(fn: ast.AST):
    """Yield nodes of one function scope without descending into nested
    function/class definitions (they are analyzed as their own scopes)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
        ):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _is_jax_call_root(name: str | None) -> bool:
    if not name:
        return False
    root = name.split(".", 1)[0]
    if root not in ("jnp", "jax"):
        return False
    return name not in ("jax.device_get",)


def _jit_wrap_info(call: ast.Call) -> _JitInfo | None:
    """`jax.jit(f, ...)` / `partial(jax.jit, ...)` -> static/donate info."""
    name = call_root(call) or ""
    if name in ("jax.jit", "jit", "pjit", "jax.pjit"):
        return _extract_argspec(call)
    if name.rsplit(".", 1)[-1] == "partial" and call.args:
        from areal_tpu.analysis.core import dotted_name

        inner = dotted_name(call.args[0]) or ""
        if inner in ("jax.jit", "jit", "jax.pjit", "pjit"):
            return _extract_argspec(call)
    return None


def _extract_argspec(call: ast.Call) -> _JitInfo:
    info = _JitInfo(line=call.lineno)
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            info.static_argnums |= _int_tuple(kw.value)
        elif kw.arg == "static_argnames":
            info.static_argnames |= _str_tuple(kw.value)
        elif kw.arg == "donate_argnums":
            info.donate_argnums |= _int_tuple(kw.value)
        elif kw.arg == "donate_argnames":
            info.donate_argnames |= _str_tuple(kw.value)
    return info


def _int_tuple(node: ast.AST) -> set:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        return {
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, int)
        }
    return set()


def _str_tuple(node: ast.AST) -> set:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        return {
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        }
    return set()


def _target_names(node: ast.AST) -> list[str]:
    if isinstance(node, ast.Name):
        return [node.id]
    if isinstance(node, (ast.Tuple, ast.List)):
        out: list[str] = []
        for e in node.elts:
            out += _target_names(e)
        return out
    return []


def _expr_key(node: ast.AST) -> str | None:
    """Stable textual key for a Name or dotted attribute (incl. self.*)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        inner = _expr_key(node.value)
        return f"{inner}.{node.attr}" if inner else None
    return None


def analyze_jax(sf: SourceFile) -> list[Finding]:
    findings: list[Finding] = []
    module_jitted = _collect_jitted(sf.tree.body)

    def walk_defs(body: list, qual: str, jitted: dict):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{qual}.{node.name}" if qual else node.name
                inner = dict(jitted)
                inner.update(_collect_jitted(node.body))
                findings.extend(_analyze_function(sf, node, q, inner))
                walk_defs(node.body, q, inner)
            elif isinstance(node, ast.ClassDef):
                q = f"{qual}.{node.name}" if qual else node.name
                findings.extend(_analyze_class_alias(sf, node, q))
                walk_defs(node.body, q, jitted)

    walk_defs(sf.tree.body, "", module_jitted)
    return findings


def _collect_jitted(body: list) -> dict[str, _JitInfo]:
    """name -> jit info for `f = jax.jit(g, ...)` bindings and decorated
    defs in one statement list."""
    out: dict[str, _JitInfo] = {}
    for node in body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            info = _jit_wrap_info(node.value)
            if info is not None:
                for t in node.targets:
                    for nm in _target_names(t):
                        out[nm] = info
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    info = _jit_wrap_info(dec)
                    if info is not None:
                        out[node.name] = info
                else:
                    from areal_tpu.analysis.core import dotted_name

                    if (dotted_name(dec) or "") in ("jax.jit", "jit"):
                        out[node.name] = _JitInfo(line=node.lineno)
    return out


def _analyze_function(
    sf: SourceFile,
    fn: ast.FunctionDef,
    qual: str,
    jitted: dict[str, _JitInfo],
) -> list[Finding]:
    findings: list[Finding] = []

    # -- scope inference: device-typed locals, stores/loads --------------
    device_names: set[str] = set()
    stores: dict[str, list[int]] = {}
    loads: dict[str, list[int]] = {}
    for node in walk_scope(fn):
        if isinstance(node, ast.Assign):
            val_device = _produces_device(node.value, jitted)
            for t in node.targets:
                for nm in _target_names(t):
                    stores.setdefault(nm, []).append(node.lineno)
                    if val_device:
                        device_names.add(nm)
        elif isinstance(node, ast.AugAssign):
            for nm in _target_names(node.target):
                stores.setdefault(nm, []).append(node.lineno)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            loads.setdefault(node.id, []).append(node.lineno)
        elif isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Load):
            key = _expr_key(node)
            if key:
                loads.setdefault(key, []).append(node.lineno)

    def is_device(expr: ast.AST) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in device_names
        if isinstance(expr, ast.Call):
            return _produces_device(expr, jitted)
        if isinstance(expr, ast.Subscript):
            return is_device(expr.value)
        return False

    # -- loop-scoped checks (AR201, AR204) -------------------------------
    def check_call(node: ast.Call, loop_vars: set[str], in_loop: bool):
        name = call_root(node) or ""
        last = name.rsplit(".", 1)[-1]
        if not in_loop:
            pass
        elif (
            last == "item"
            and isinstance(node.func, ast.Attribute)
            and not node.args
            and is_device(node.func.value)
        ):
            findings.append(
                Finding(
                    "AR201",
                    sf.display,
                    node.lineno,
                    f"{qual}.item",
                    ".item() on a device array inside a loop forces a "
                    "device->host sync every iteration",
                )
            )
        elif (
            name in _HOST_SYNC_CASTS
            and len(node.args) == 1
            and is_device(node.args[0])
        ):
            key = _expr_key(node.args[0]) or name
            findings.append(
                Finding(
                    "AR201",
                    sf.display,
                    node.lineno,
                    f"{qual}.{key}",
                    f"{name}() on device array '{key}' inside a loop blocks "
                    "on the device every iteration; hoist the transfer out "
                    "of the loop or keep the value on device",
                )
            )
        elif name in _NP_CONVERTERS and node.args and is_device(node.args[0]):
            key = _expr_key(node.args[0]) or "expr"
            findings.append(
                Finding(
                    "AR201",
                    sf.display,
                    node.lineno,
                    f"{qual}.{key}",
                    f"{name}() of device array '{key}' inside a loop is an "
                    "implicit blocking transfer every iteration",
                )
            )
        info = jitted.get(name)
        if info is not None and in_loop and loop_vars:
            for i, arg in enumerate(node.args):
                free = {
                    n.id for n in ast.walk(arg) if isinstance(n, ast.Name)
                }
                wrapped = isinstance(arg, ast.Call) and _is_jax_call_root(
                    call_root(arg)
                )
                if free & loop_vars and not wrapped:
                    findings.append(
                        Finding(
                            "AR204",
                            sf.display,
                            node.lineno,
                            f"{qual}.{name}.arg{i}",
                            f"loop-varying Python value "
                            f"{ast.unparse(arg)!r} passed to jit-compiled "
                            f"'{name}' — each new value re-specializes the "
                            "computation (retrace per iteration); pass a "
                            "device array or declare it static and bucket "
                            "it",
                        )
                    )
        if info is not None:
            for i, arg in enumerate(node.args):
                if i in info.static_argnums and isinstance(
                    arg, (ast.List, ast.Dict, ast.Set)
                ):
                    findings.append(
                        Finding(
                            "AR204",
                            sf.display,
                            node.lineno,
                            f"{qual}.{name}.arg{i}",
                            f"unhashable literal passed at static arg "
                            f"position {i} of jit-compiled '{name}'",
                        )
                    )

    def scan(node: ast.AST, loop_vars: set[str], in_loop: bool):
        for ch in ast.iter_child_nodes(node):
            if isinstance(
                ch,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef),
            ):
                continue
            if isinstance(ch, ast.For):
                scan(ch, loop_vars | set(_target_names(ch.target)), True)
                continue
            if isinstance(ch, ast.While):
                scan(ch, loop_vars, True)
                continue
            if isinstance(ch, ast.Call):
                check_call(ch, loop_vars, in_loop)
            scan(ch, loop_vars, in_loop)

    scan(fn, set(), False)

    # -- AR202: donated buffer reuse -------------------------------------
    for node in walk_scope(fn):
        if not isinstance(node, ast.Call):
            continue
        name = call_root(node) or ""
        info = jitted.get(name)
        if info is None or not (info.donate_argnums or info.donate_argnames):
            continue
        donated: list[tuple[str, int]] = []
        for i, arg in enumerate(node.args):
            if i in info.donate_argnums:
                key = _expr_key(arg)
                if key:
                    donated.append((key, node.lineno))
        for kw in node.keywords:
            if kw.arg in info.donate_argnames:
                key = _expr_key(kw.value)
                if key:
                    donated.append((key, node.lineno))
        for key, line in donated:
            rebinds = [ln for ln in stores.get(key, []) if ln >= line]
            for ld in sorted(loads.get(key, [])):
                if ld <= line:
                    continue
                if any(r <= ld for r in rebinds):
                    break
                findings.append(
                    Finding(
                        "AR202",
                        sf.display,
                        ld,
                        f"{qual}.{key}",
                        f"'{key}' was donated to '{name}' at line {line} "
                        "and read afterwards — donation deletes the "
                        "buffer (use the returned array instead)",
                    )
                )
                break

    # -- AR203: aliased upload then in-place mutation (same scope) -------
    uploads: list[tuple[str, int]] = []
    for node in walk_scope(fn):
        if (
            isinstance(node, ast.Call)
            and (call_root(node) or "") == "jnp.asarray"
            and node.args
        ):
            key = _expr_key(node.args[0])
            if key and not is_device(node.args[0]):
                uploads.append((key, node.lineno))
    if uploads:
        mutations = _inplace_mutations(fn)
        for key, line in uploads:
            later = [
                (ln, how) for (k, ln, how) in mutations if k == key and ln > line
            ]
            if not later:
                continue
            ln, how = later[0]
            if any(line < r <= ln for r in _name_rebinds(fn, key)):
                continue
            findings.append(
                Finding(
                    "AR203",
                    sf.display,
                    line,
                    f"{qual}.{key}",
                    f"jnp.asarray({key}) may zero-copy the host buffer, but "
                    f"'{key}' is mutated in place at line {ln} ({how}) — "
                    "the in-flight computation reads the mutation; upload "
                    f"an explicit copy (jnp.asarray(np.array({key})))",
                )
            )
    return findings


def _analyze_class_alias(
    sf: SourceFile, cls: ast.ClassDef, qual: str
) -> list[Finding]:
    """Cross-method AR203 for self.* attributes: an aliased upload of
    `self.X` in one method + an in-place mutation of `self.X` in any
    method of the same class (call order is unknowable statically)."""
    findings: list[Finding] = []
    uploads: list[tuple[str, int, str]] = []
    mutations: list[tuple[str, int, str]] = []
    for m in cls.body:
        if not isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(m):
            if (
                isinstance(node, ast.Call)
                and (call_root(node) or "") == "jnp.asarray"
                and node.args
            ):
                key = _expr_key(node.args[0])
                if key and key.startswith("self."):
                    uploads.append((key, node.lineno, m.name))
        for k, ln, how in _inplace_mutations(m):
            if k.startswith("self."):
                mutations.append((k, ln, how))
    mutated = {k for k, _, _ in mutations}
    for key, line, mname in uploads:
        if key in mutated:
            mline = next(ln for k, ln, _ in mutations if k == key)
            findings.append(
                Finding(
                    "AR203",
                    sf.display,
                    line,
                    f"{qual}.{key}",
                    f"jnp.asarray({key}) in {mname}() may zero-copy a host "
                    "mirror that is mutated in place elsewhere in the class "
                    f"(e.g. line {mline}); upload an explicit copy",
                )
            )
    return findings


def _inplace_mutations(fn: ast.AST) -> list[tuple[str, int, str]]:
    """(key, line, kind) for `X[...] =` / `X[...] op=` / `X op=` /
    `X.fill()`-style in-place mutations within `fn` (nested defs
    included — closures mutate enclosing-scope arrays)."""
    out: list[tuple[str, int, str]] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    key = _expr_key(t.value)
                    if key:
                        out.append((key, node.lineno, "subscript assign"))
        elif isinstance(node, ast.AugAssign):
            if isinstance(node.target, ast.Subscript):
                key = _expr_key(node.target.value)
                if key:
                    out.append((key, node.lineno, "subscript augassign"))
            else:
                key = _expr_key(node.target)
                if key:
                    out.append((key, node.lineno, "augassign"))
        elif isinstance(node, ast.Call):
            name = call_root(node) or ""
            parts = name.rsplit(".", 1)
            if len(parts) == 2 and parts[1] in _INPLACE_MUTATORS:
                out.append((parts[0], node.lineno, f".{parts[1]}()"))
    return out


def _name_rebinds(fn: ast.AST, key: str) -> list[int]:
    out = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if _expr_key(t) == key:
                    out.append(node.lineno)
    return out


def _produces_device(expr: ast.AST, jitted: dict[str, _JitInfo]) -> bool:
    if not isinstance(expr, ast.Call):
        return False
    name = call_root(expr)
    if name is None:
        # immediately-invoked jit: jax.jit(f)(x)
        if isinstance(expr.func, ast.Call) and _jit_wrap_info(expr.func):
            return True
        return False
    if name in jitted:
        return True
    if _is_jax_call_root(name):
        last = name.rsplit(".", 1)[-1]
        if last in ("device_get",):
            return False
        return True
    return False
