"""AR1xx (robustness half) — swallowed-exception analysis.

AR106: a broad `except` (bare, `Exception`, or `BaseException`) whose body
neither re-raises, nor logs, nor keeps the exception object alive for a
later handler is a SILENT SWALLOW: the failure vanishes and the system
degrades invisibly — the exact rot the fault-injection harness exists to
expose (a seam that fires into a swallowing handler looks like a pass).

The rule runs over the fault-bearing packages only — `areal_tpu/core/`,
`areal_tpu/launcher/`, `areal_tpu/engine/` — where an invisible failure
corrupts rollout accounting, weight staging, or KV state. Paths outside
the `areal_tpu/` tree (seeded test fixtures) are always checked.

A handler is NOT a swallow when its body contains any of:
  - a `raise` statement (re-raise or translate),
  - a logging call: any call whose dotted callee mentions a logger-ish
    root (`logger`, `logging`, `log`, `warnings`, `traceback`) or a
    level method (`.debug/.info/.warning/.error/.exception/.critical/
    .warn/.print_exc`),
  - any reference to the bound exception name (`last_exc = e`, `_put(e)`,
    `callback(e)` — the error is preserved or delegated, not dropped).

Suppression: inline pragma `# areal-lint: disable=AR106`, file pragma, or
a baseline entry keyed on `<qualname>.except#<n>` (ordinal among the
function's broad handlers — stable across unrelated edits).
"""

from __future__ import annotations

import ast

from areal_tpu.analysis.core import Finding, SourceFile

_BROAD = {"Exception", "BaseException"}
_LOGGY_ROOTS = {"logger", "logging", "log", "warnings", "traceback"}
_LOGGY_METHODS = {
    "debug",
    "info",
    "warning",
    "error",
    "exception",
    "critical",
    "warn",
    "print_exc",
    "log",
}

# rule scope: only these packages carry cross-component fault seams
_SCOPED_PKGS = ("areal_tpu/core/", "areal_tpu/launcher/", "areal_tpu/engine/")


def _in_scope(display_path: str) -> bool:
    p = display_path.replace("\\", "/")
    if "areal_tpu/" not in p:
        return True  # fixtures / explicit single-file runs
    return any(pkg in p for pkg in _SCOPED_PKGS)


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    names: list[ast.expr] = list(t.elts) if isinstance(t, ast.Tuple) else [t]
    for n in names:
        if isinstance(n, ast.Name) and n.id in _BROAD:
            return True
        if isinstance(n, ast.Attribute) and n.attr in _BROAD:
            return True  # builtins.Exception
    return False


def _call_is_loggy(call: ast.Call) -> bool:
    fn = call.func
    parts: list[str] = []
    node = fn
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    if not parts:
        return False
    root = parts[-1]
    leaf = parts[0]
    return root in _LOGGY_ROOTS or leaf in _LOGGY_METHODS


def _handler_swallows(handler: ast.ExceptHandler) -> bool:
    exc_name = handler.name
    for stmt in handler.body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Raise):
                return False
            if isinstance(node, ast.Call) and _call_is_loggy(node):
                return False
            # `last_exc = e` / `_put(e)` / `cb(e)`: the error object is
            # preserved or delegated — a later decision sees it
            if isinstance(node, ast.Name) and exc_name and node.id == exc_name:
                return False
    return True


class _Walker(ast.NodeVisitor):
    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.stack: list[str] = []
        self.findings: list[Finding] = []
        # per-qualname ordinal so the baseline key survives line churn
        self._ordinals: dict[str, int] = {}

    def _qualname(self) -> str:
        return ".".join(self.stack) or "<module>"

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def _visit_fn(self, node) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_Try(self, node: ast.Try) -> None:
        for handler in node.handlers:
            if _is_broad(handler) and _handler_swallows(handler):
                qn = self._qualname()
                n = self._ordinals.get(qn, 0)
                self._ordinals[qn] = n + 1
                caught = "bare" if handler.type is None else "Exception"
                self.findings.append(
                    Finding(
                        rule="AR106",
                        file=self.sf.display,
                        line=handler.lineno,
                        key=f"{qn}.except#{n}",
                        message=(
                            f"broad `except {caught}` swallows the "
                            "failure: no raise, no log, exception not "
                            "preserved — a fault seam firing here "
                            "degrades the system invisibly"
                        ),
                    )
                )
        self.generic_visit(node)


def analyze_robustness(sf: SourceFile) -> list[Finding]:
    if not _in_scope(sf.display):
        return []
    w = _Walker(sf)
    w.visit(sf.tree)
    return w.findings
