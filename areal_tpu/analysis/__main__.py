"""areal-lint CLI.

  python -m areal_tpu.analysis [paths...]
      [--baseline tools/lint_baseline.json] [--write-baseline]
      [--rules AR101,AR2xx...] [--json] [--list-rules] [--no-baseline]

Exit codes: 0 clean (all findings baselined or none), 1 findings, 2 usage.
The default baseline path is tools/lint_baseline.json relative to the
current directory (the repo root in CI); pass --no-baseline to see every
finding, --write-baseline to (re)generate the file from current findings.
"""

from __future__ import annotations

import argparse
import json
import sys

from areal_tpu.analysis.core import RULES, Baseline, analyze_paths

DEFAULT_BASELINE = "tools/lint_baseline.json"


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m areal_tpu.analysis",
        description="concurrency + JAX hot-path + wire-contract analyzer",
    )
    p.add_argument("paths", nargs="*", default=["areal_tpu"])
    p.add_argument("--baseline", default=None, help="baseline JSON path")
    p.add_argument(
        "--no-baseline", action="store_true", help="ignore any baseline"
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="write current findings to the baseline file and exit 0",
    )
    p.add_argument(
        "--rules", default=None, help="comma-separated rule filter (AR101,...)"
    )
    p.add_argument("--json", action="store_true", help="machine output")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        for code, desc in sorted(RULES.items()):
            print(f"{code}  {desc}")
        return 0

    rules = None
    if args.rules:
        rules = set()
        for r in args.rules.split(","):
            r = r.strip().upper()
            if r.endswith("XX"):  # family: AR1xx / AR2xx
                rules |= {c for c in RULES if c.startswith(r[:-2])}
            elif r:
                rules.add(r)

    paths = args.paths or ["areal_tpu"]
    errors: list = []
    findings = analyze_paths(paths, rules=rules, collect_errors=errors)
    for path, err in errors:
        print(f"warning: could not parse {path}: {err}", file=sys.stderr)

    baseline_path = args.baseline or DEFAULT_BASELINE
    if args.write_baseline:
        Baseline.from_findings(findings).save(baseline_path)
        print(f"wrote {len(findings)} entries to {baseline_path}")
        return 0

    baseline = None
    if not args.no_baseline:
        try:
            baseline = Baseline.load(baseline_path)
        except FileNotFoundError:
            baseline = None
        except (OSError, ValueError) as e:
            print(f"error: bad baseline {baseline_path}: {e}", file=sys.stderr)
            return 2

    new = [f for f in findings if baseline is None or not baseline.covers(f)]
    suppressed = len(findings) - len(new)

    invalid = baseline.invalid() if baseline else []
    if args.json:
        print(
            json.dumps(
                {
                    "findings": [f.__dict__ for f in new],
                    "baselined": suppressed,
                    "total": len(findings),
                    "invalid_baseline": [dict(e) for e in invalid],
                }
            )
        )
    else:
        for f in new:
            print(f.format())
        stale = baseline.unused(findings) if baseline else []
        for e in stale:
            print(
                "note: stale baseline entry "
                f"{e.get('file')}:{e.get('rule')}:{e.get('key')} "
                "(finding no longer fires — remove it)",
                file=sys.stderr,
            )
        for e in invalid:
            print(
                "note: invalid baseline entry "
                f"{e.get('file')}:{e.get('rule')}:{e.get('key')} "
                "(justification empty or still the "
                f"{'TODO: justify or fix'!r} placeholder — justify or fix)",
                file=sys.stderr,
            )
        print(
            f"areal-lint: {len(new)} finding(s), {suppressed} baselined, "
            f"{len(findings)} total",
            file=sys.stderr,
        )
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
