"""AR1xx — concurrency invariants over the async engine surface.

Model (documented in docs/ANALYSIS.md):

Thread contexts, per class:
  - "main"                 any public sync method (external callers)
  - "eventloop"            any `async def` method (one event loop = one
                           thread; aiohttp handler registrations are
                           discovered and land here too)
  - "thread:<entry>"       a method or nested function passed to
                           `threading.Thread(target=...)`,
                           `<executor>.submit(...)`, or
                           `loop.run_in_executor(None, ...)`
Contexts propagate through `self.m()` calls (fixpoint), so a private helper
called from both the scheduler thread and a public method is multi-context.
`__init__` bodies are excluded (they run before any thread exists) but
thread-target functions *defined* inside `__init__` are not.

AR101: an attribute written from >= 2 contexts must be guarded. A guard is
  - implicit: every multi-context write site sits lexically inside a
    `with self.<lock>:` block on one common lock, or
  - declared: `# guarded-by: <lock>` on an assignment line of the attr, or
    a module-level `_GUARDED_BY = {"Class.attr": "<lock>"}` registry (for
    handshake-style serialization the lexical check cannot see).
Attributes whose initializer is a known thread-safe type (Lock/Event/Queue/
OrderedLock/...) are exempt.

AR102: cycle in the global lock acquisition-order graph. An edge A -> B is
recorded whenever B is acquired while A is held, including one level of
interprocedural reach (locks transitively acquired by `self.m()` calls made
under A). The graph is unioned across every analyzed file before cycle
detection.

AR103: an edge A -> B where both locks declare ranks (`OrderedLock(name,
rank)`) in the same class and rank(A) >= rank(B) — the static counterpart
of utils/lock.py's runtime LockOrderViolation.

AR104: a guarded-by annotation or registry entry naming a lock that is not
declared on the class (or a registry key naming an unknown class/attr).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from areal_tpu.analysis.core import (
    GUARDED_BY_RE,
    Finding,
    SourceFile,
    call_root,
    dotted_name,
)

# attribute initializers considered inherently thread-safe
_SAFE_TYPES = {
    "Lock",
    "RLock",
    "Condition",
    "Event",
    "Semaphore",
    "BoundedSemaphore",
    "Barrier",
    "Queue",
    "LifoQueue",
    "PriorityQueue",
    "SimpleQueue",
    "OrderedLock",
    "local",
}
_LOCK_TYPES = {"Lock", "RLock", "Condition", "OrderedLock"}

# method calls that mutate their receiver
_MUTATORS = {
    "append",
    "extend",
    "insert",
    "pop",
    "popleft",
    "appendleft",
    "remove",
    "discard",
    "add",
    "clear",
    "update",
    "setdefault",
    "sort",
    "reverse",
    "fill",
}


@dataclass
class _Write:
    unit: str
    line: int
    held: frozenset  # lock node names lexically held at the write


@dataclass
class _ClassInfo:
    name: str
    file: str
    methods: dict = field(default_factory=dict)  # name -> FunctionDef
    locks: dict = field(default_factory=dict)  # attr -> {"rank", "line"}
    safe_attrs: set = field(default_factory=set)
    writes: dict = field(default_factory=dict)  # attr -> [_Write]
    entry_ctx: dict = field(default_factory=dict)  # unit -> set[str]
    calls: dict = field(default_factory=dict)  # unit -> set[method name]
    annotations: dict = field(default_factory=dict)  # attr -> (lock, line)
    attr_lines: dict = field(default_factory=dict)  # attr -> first write line


class ConcurrencyState:
    """Cross-file accumulator for the lock-order graph (AR102/AR103)."""

    def __init__(self):
        # (held, acquired) -> (file, line) of a representative site
        self.edges: dict[tuple[str, str], tuple[str, int]] = {}
        self.ranks: dict[str, int] = {}  # lock node -> declared rank
        self._files: dict[str, SourceFile] = {}

    def add_edge(self, held: str, acquired: str, file: str, line: int):
        self.edges.setdefault((held, acquired), (file, line))

    def finalize(self) -> list[Finding]:
        findings: list[Finding] = []
        # AR103: rank order, same-class locks only
        for (a, b), (file, line) in sorted(self.edges.items()):
            ra, rb = self.ranks.get(a), self.ranks.get(b)
            if ra is None or rb is None or a == b:
                continue
            if a.rsplit(".", 1)[0] != b.rsplit(".", 1)[0]:
                continue
            if ra >= rb:
                f = Finding(
                    rule="AR103",
                    file=file,
                    line=line,
                    key=f"{a}->{b}",
                    message=f"acquiring {b} (rank {rb}) while holding "
                    f"{a} (rank {ra}) violates the declared order",
                )
                if not self._suppressed(f):
                    findings.append(f)
        # AR102: cycles over the union graph
        adj: dict[str, set[str]] = {}
        for a, b in self.edges:
            if a != b:
                adj.setdefault(a, set()).add(b)
        seen_cycles: set[frozenset] = set()
        for start in sorted(adj):
            cyc = self._find_cycle(start, adj)
            if cyc and frozenset(cyc) not in seen_cycles:
                seen_cycles.add(frozenset(cyc))
                edge = (cyc[0], cyc[1 % len(cyc)])
                file, line = self.edges.get(
                    edge, next(iter(self.edges.values()))
                )
                f = Finding(
                    rule="AR102",
                    file=file,
                    line=line,
                    key="->".join(sorted(set(cyc))),
                    message="lock acquisition-order cycle: "
                    + " -> ".join(cyc + [cyc[0]]),
                )
                if not self._suppressed(f):
                    findings.append(f)
        return findings

    @staticmethod
    def _find_cycle(start: str, adj: dict) -> list[str] | None:
        path: list[str] = []
        on_path: set[str] = set()
        done: set[str] = set()

        def dfs(n: str) -> list[str] | None:
            path.append(n)
            on_path.add(n)
            for m in sorted(adj.get(n, ())):
                if m in on_path:
                    return path[path.index(m) :]
                if m not in done:
                    got = dfs(m)
                    if got:
                        return got
            on_path.discard(n)
            done.add(n)
            path.pop()
            return None

        return dfs(start)

    def _suppressed(self, f: Finding) -> bool:
        sf = self._files.get(f.file)
        return sf.suppressed(f.rule, f.line) if sf else False


def analyze_concurrency(
    sf: SourceFile, state: ConcurrencyState | None = None
) -> list[Finding]:
    if state is not None:
        state._files[sf.display] = sf
    findings: list[Finding] = []
    module_locks = _module_locks(sf.tree)
    registry, registry_lines = _guard_registry(sf.tree)
    classes = [
        n for n in sf.tree.body if isinstance(n, ast.ClassDef)
    ]
    class_names = {c.name for c in classes}
    for cls in classes:
        info = _collect_class(sf, cls)
        if state is not None:
            for attr, meta in info.locks.items():
                if meta["rank"] is not None:
                    state.ranks[f"{info.name}.{attr}"] = meta["rank"]
        findings += _check_class(
            sf, info, registry, module_locks, state
        )
    # AR104 for registry keys that name unknown classes/locks
    for key, lock in registry.items():
        cls_name = key.split(".", 1)[0]
        if cls_name not in class_names:
            findings.append(
                Finding(
                    rule="AR104",
                    file=sf.display,
                    line=registry_lines.get(key, 1),
                    key=key,
                    message=f"_GUARDED_BY entry {key!r} names a class not "
                    "defined in this module",
                )
            )
    # lock-order edges for module-level functions (module-level locks)
    if state is not None:
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _walk_unit(
                    node.body,
                    unit="",
                    info=None,
                    sf=sf,
                    state=state,
                    lock_nodes=module_locks,
                    held=[],
                )
    return findings


# -- collection --------------------------------------------------------------


def _type_of_call(node: ast.AST) -> str | None:
    if isinstance(node, ast.Call):
        name = call_root(node)
        if name:
            return name.rsplit(".", 1)[-1]
    return None


def _module_locks(tree: ast.Module) -> dict[str, str]:
    """module-level `NAME = threading.Lock()` -> {NAME: node_name}."""
    out: dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name) and _type_of_call(node.value) in _LOCK_TYPES:
                out[t.id] = f"<module>.{t.id}"
    return out


def _guard_registry(tree: ast.Module):
    """module-level `_GUARDED_BY = {"Class.attr": "lock"}`."""
    reg: dict[str, str] = {}
    lines: dict[str, int] = {}
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        t = node.targets[0]
        if not (isinstance(t, ast.Name) and t.id == "_GUARDED_BY"):
            continue
        if isinstance(node.value, ast.Dict):
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) and isinstance(v, ast.Constant):
                    reg[str(k.value)] = str(v.value)
                    lines[str(k.value)] = k.lineno
    return reg, lines


def _self_attr(node: ast.AST) -> str | None:
    """`self.X` -> "X" (one level only)."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _thread_target(call: ast.Call):
    """If `call` hands a callable to another thread, return that callable's
    AST expr: Thread(target=...), <pool>.submit(fn, ...),
    loop.run_in_executor(exec, fn, ...)."""
    name = call_root(call) or ""
    last = name.rsplit(".", 1)[-1]
    if last == "Thread":
        for kw in call.keywords:
            if kw.arg == "target":
                return kw.value
    elif last == "submit" and call.args:
        return call.args[0]
    elif last == "run_in_executor" and len(call.args) >= 2:
        return call.args[1]
    return None


def _collect_class(sf: SourceFile, cls: ast.ClassDef) -> _ClassInfo:
    info = _ClassInfo(name=cls.name, file=sf.display)
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info.methods[node.name] = node

    # pass A: locks, safe attrs, annotations, thread entries
    for mname, m in info.methods.items():
        for node in ast.walk(m):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    ty = _type_of_call(node.value)
                    if ty in _LOCK_TYPES:
                        rank = _ordered_lock_rank(node.value)
                        info.locks.setdefault(
                            attr, {"rank": rank, "line": node.lineno}
                        )
                    if ty in _SAFE_TYPES:
                        info.safe_attrs.add(attr)
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    attr = _self_attr(t)
                    if attr is None or node.lineno > len(sf.lines):
                        continue
                    mm = GUARDED_BY_RE.search(sf.lines[node.lineno - 1])
                    if mm:
                        info.annotations[attr] = (mm.group(1), node.lineno)
            if isinstance(node, ast.Call):
                tgt = _thread_target(node)
                if tgt is None:
                    continue
                tattr = _self_attr(tgt)
                if tattr and tattr in info.methods:
                    info.entry_ctx.setdefault(tattr, set()).add(
                        f"thread:{tattr}"
                    )
                elif isinstance(tgt, ast.Name):
                    # nested function used as a thread target
                    info.entry_ctx.setdefault(
                        f"{mname}.{tgt.id}", set()
                    ).add(f"thread:{mname}.{tgt.id}")

    # entry contexts for methods themselves
    for mname, m in info.methods.items():
        ctx = info.entry_ctx.setdefault(mname, set())
        if isinstance(m, ast.AsyncFunctionDef):
            ctx.add("eventloop")
        elif mname == "__init__":
            pass  # runs before any thread exists
        elif not mname.startswith("_") or (
            mname.startswith("__") and mname.endswith("__")
        ):
            ctx.add("main")
    return info


def _ordered_lock_rank(call: ast.Call) -> int | None:
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        v = call.args[1].value
        return v if isinstance(v, int) else None
    for kw in call.keywords:
        if kw.arg == "rank" and isinstance(kw.value, ast.Constant):
            v = kw.value.value
            return v if isinstance(v, int) else None
    return None


# -- per-unit walk (writes, self-calls, lock edges) --------------------------


def _lock_node_of(expr: ast.AST, info, lock_nodes: dict[str, str]) -> str | None:
    """Resolve a with-item / acquire receiver to a lock graph node name."""
    attr = _self_attr(expr)
    if attr is not None and info is not None and attr in info.locks:
        return f"{info.name}.{attr}"
    if isinstance(expr, ast.Name) and expr.id in lock_nodes:
        return lock_nodes[expr.id]
    if isinstance(expr, ast.Call):
        name = call_root(expr) or ""
        if name.rsplit(".", 1)[-1] == "DistributedLock":
            if expr.args and isinstance(expr.args[0], ast.Constant):
                return f"DistributedLock:{expr.args[0].value}"
            return "DistributedLock:<dynamic>"
    return None


def _walk_unit(
    body: list,
    unit: str,
    info: _ClassInfo | None,
    sf: SourceFile,
    state: ConcurrencyState | None,
    lock_nodes: dict[str, str],
    held: list[str],
):
    """Walk statements of one execution unit, tracking lexically held
    locks; record writes/calls into `info` and edges into `state`."""

    def record_write(attr: str, line: int):
        if info is None or unit.split(".", 1)[0] == "__init__" and "." not in unit:
            return
        info.writes.setdefault(attr, []).append(
            _Write(unit=unit, line=line, held=frozenset(held))
        )
        info.attr_lines.setdefault(attr, line)

    def record_call(callee: str):
        if info is not None:
            info.calls.setdefault(unit, set()).add(callee)

    def visit(node: ast.AST):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: thread targets become their own unit with an
            # empty held stack (a fresh thread holds nothing)
            nested = f"{unit.split('.', 1)[0]}.{node.name}" if info else unit
            if info is not None and nested in info.entry_ctx:
                _walk_unit(
                    node.body, nested, info, sf, state, lock_nodes, []
                )
            else:
                for ch in node.body:
                    visit(ch)
            return
        if isinstance(node, ast.Lambda):
            return  # opaque
        if isinstance(node, (ast.With, ast.AsyncWith)):
            n0 = len(held)
            for item in node.items:
                lock = _lock_node_of(item.context_expr, info, lock_nodes)
                if lock is not None:
                    if state is not None:
                        for h in held:
                            state.add_edge(
                                h, lock, sf.display, item.context_expr.lineno
                            )
                    held.append(lock)
                else:
                    visit(item.context_expr)
            for ch in node.body:
                visit(ch)
            del held[n0:]
            return
        if isinstance(node, ast.Call):
            name = call_root(node) or ""
            last = name.rsplit(".", 1)[-1]
            # explicit .acquire(): held for the rest of the unit (approx.)
            if last == "acquire" and isinstance(node.func, ast.Attribute):
                lock = _lock_node_of(node.func.value, info, lock_nodes)
                if lock is not None:
                    if state is not None:
                        for h in held:
                            state.add_edge(h, lock, sf.display, node.lineno)
                    held.append(lock)
            # mutating method call on a self attribute
            if last in _MUTATORS and isinstance(node.func, ast.Attribute):
                attr = _self_attr(node.func.value)
                if attr is not None:
                    record_write(attr, node.lineno)
            # self.m() call
            if (
                name.startswith("self.")
                and name.count(".") == 1
                and info is not None
                and name[5:] in info.methods
            ):
                record_call(name[5:])
                if state is not None and held:
                    # interprocedural edges resolved in _check_class via
                    # transitive acquires; record the call site for that
                    info.calls.setdefault(unit, set())
                    _pending_edges.append(
                        (info.name, list(held), name[5:], sf.display, node.lineno)
                    )
            for ch in ast.iter_child_nodes(node):
                visit(ch)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
            )
            flat: list[ast.AST] = []
            for t in targets:
                if isinstance(t, (ast.Tuple, ast.List)):
                    flat.extend(t.elts)
                else:
                    flat.append(t)
            for t in flat:
                attr = _self_attr(t)
                if attr is not None:
                    record_write(attr, node.lineno)
                elif isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr is not None:
                        record_write(attr, node.lineno)
            for ch in ast.iter_child_nodes(node):
                visit(ch)
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                attr = _self_attr(t)
                if attr is None and isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                if attr is not None:
                    record_write(attr, node.lineno)
            return
        for ch in ast.iter_child_nodes(node):
            visit(ch)

    for stmt in body:
        visit(stmt)


# pending interprocedural (held-locks, callee) records; resolved per class
_pending_edges: list = []


# -- evaluation --------------------------------------------------------------


def _check_class(
    sf: SourceFile,
    info: _ClassInfo,
    registry: dict[str, str],
    module_locks: dict[str, str],
    state: ConcurrencyState | None,
) -> list[Finding]:
    global _pending_edges
    _pending_edges = []
    for mname, m in info.methods.items():
        _walk_unit(m.body, mname, info, sf, state, module_locks, [])

    # context propagation through self.m() calls (fixpoint)
    ctx: dict[str, set[str]] = {
        u: set(c) for u, c in info.entry_ctx.items()
    }
    changed = True
    while changed:
        changed = False
        for unit, callees in info.calls.items():
            src = ctx.get(unit, set())
            if not src:
                continue
            for callee in callees:
                dst = ctx.setdefault(callee, set())
                if not src <= dst:
                    dst |= src
                    changed = True

    # transitive lock acquires per method (for interprocedural edges)
    if state is not None:
        direct: dict[str, set[str]] = {}
        for mname, m in info.methods.items():
            acq: set[str] = set()
            for node in ast.walk(m):
                if isinstance(node, ast.With):
                    for item in node.items:
                        lock = _lock_node_of(
                            item.context_expr, info, module_locks
                        )
                        if lock:
                            acq.add(lock)
                elif isinstance(node, ast.Call):
                    nm = call_root(node) or ""
                    if nm.rsplit(".", 1)[-1] == "acquire" and isinstance(
                        node.func, ast.Attribute
                    ):
                        lock = _lock_node_of(
                            node.func.value, info, module_locks
                        )
                        if lock:
                            acq.add(lock)
            direct[mname] = acq
        trans = {m: set(a) for m, a in direct.items()}
        changed = True
        while changed:
            changed = False
            for mname, m in info.methods.items():
                callees = set()
                for unit, cs in info.calls.items():
                    if unit.split(".", 1)[0] == mname:
                        callees |= cs
                for c in callees:
                    extra = trans.get(c, set())
                    if not extra <= trans[mname]:
                        trans[mname] |= extra
                        changed = True
        for cls_name, held, callee, file, line in _pending_edges:
            if cls_name != info.name:
                continue
            for lock in trans.get(callee, ()):
                for h in held:
                    if h != lock:
                        state.add_edge(h, lock, file, line)

    findings: list[Finding] = []

    # AR104: annotations naming undeclared locks
    known_locks = set(info.locks) | set(module_locks)
    for attr, (lock, line) in sorted(info.annotations.items()):
        lname = lock[5:] if lock.startswith("self.") else lock
        if lname not in known_locks:
            findings.append(
                Finding(
                    rule="AR104",
                    file=sf.display,
                    line=line,
                    key=f"{info.name}.{attr}",
                    message=f"guarded-by names {lock!r}, which is not a "
                    f"declared lock of {info.name}",
                )
            )
    for key, lock in sorted(registry.items()):
        cls_name, _, attr = key.partition(".")
        if cls_name != info.name:
            continue
        if lock not in known_locks:
            findings.append(
                Finding(
                    rule="AR104",
                    file=sf.display,
                    line=1,
                    key=key,
                    message=f"_GUARDED_BY[{key!r}] names {lock!r}, which is "
                    f"not a declared lock of {info.name}",
                )
            )

    # AR101: multi-context writes without a guard
    for attr, writes in sorted(info.writes.items()):
        if attr in info.safe_attrs or attr in info.locks:
            continue
        write_ctxs: set[str] = set()
        for w in writes:
            write_ctxs |= ctx.get(w.unit, set())
        if len(write_ctxs) < 2:
            continue
        # implicit guard: one common lock held at every write site
        common = None
        for w in writes:
            common = w.held if common is None else (common & w.held)
        if common:
            continue
        # declared guard
        if attr in info.annotations:
            continue
        if registry.get(f"{info.name}.{attr}"):
            continue
        lines = sorted({w.line for w in writes})
        # an inline disable pragma on ANY write site suppresses the
        # attribute's finding (the finding itself is anchored to the first
        # write, which may be far from the site the author annotated)
        if any(sf.suppressed("AR101", ln) for ln in lines):
            continue
        findings.append(
            Finding(
                rule="AR101",
                file=sf.display,
                line=lines[0],
                key=f"{info.name}.{attr}",
                message=f"'{attr}' is written from contexts "
                f"{sorted(write_ctxs)} (lines {lines[:8]}) with no common "
                "lock held and no guarded-by declaration",
            )
        )
    return findings
