"""areal-lint: AST-based concurrency + JAX hot-path invariant analyzer.

CLI: `python -m areal_tpu.analysis [paths...]` (see __main__.py).
Library: `analyze_paths(paths)` returns pragma-filtered Findings.
Rule catalog and semantics: docs/ANALYSIS.md.
"""

from areal_tpu.analysis.core import (  # noqa: F401
    Baseline,
    Finding,
    RULES,
    analyze_paths,
)
