"""AR3xx — cross-component wire contracts & observability drift.

The fleet is four processes (trainer, router, decode replicas, supervisor)
stitched together by STRING-KEYED contracts: HTTP route paths, fault-seam
names, metric keys the router/supervisor poll out of `/metrics`,
`_GUARDED_BY` registry entries, and config knobs mirrored into argparse
flags. None of these are checked by the type system — a typo'd seam
pattern silently never fires, a renamed metric silently blinds the
autoscaler, a dead endpoint rots until an operator needs it. The AR3xx
family checks them statically, with the same pure-AST machinery (no
imports, no execution) as AR1xx/AR2xx.

AR301 — route pairing. Server-side registrations
  (`app.router.add_get("/x", h)` and friends) are matched against
  client-side path literals: `*_ENDPOINT = "/x"` constants, string and
  f-string arguments of HTTP-ish calls (`arequest_with_retry`,
  `aget_with_retry`, `_http_get`, ...; query strings are stripped, so
  `f"/kv_recv?xid={xid}"` pairs with the `/kv_recv` registration).
  A client path with no registration anywhere in the analyzed set is an
  unregistered-endpoint finding; a registration in `launcher/` that no
  client reaches is a dead-endpoint finding unless the line carries
  `# wire: external` (an ops/bench surface consumed outside the tree —
  the annotation IS the declared contract). Both directions are skipped
  when the analyzed set harvested no registrations at all, so a
  client-only sweep (`tools/lint.sh --all` over `bench.py`) stays quiet.

AR302 — fault-seam validity. Every `fire/afire/tear("<seam>", ...)`
  string constant is a real seam; every `FaultPoint(site=<pat>)` /
  `{"site": <pat>}` literal is an fnmatch pattern. A pattern matching
  zero harvested seams is a plan that silently never fires. A seam name
  fired from two different modules is a collision: one fnmatch pattern
  now perturbs two unrelated boundaries. Pattern checks are skipped when
  the analyzed set harvested no seams (plans live in bench/tests; seams
  live in the tree — only a combined or self-contained run can judge).

AR303 — metrics contract. Producer keys are harvested from metrics
  producers — functions named `get_metrics` / `_health` / `*_metrics`, or
  functions/assignments annotated `# metrics-producer` (for helpers and
  entry templates, like the router's breaker defaultdict, whose dicts
  ride inside `/metrics`) — plus the initializer keys of
  `self.*_stats` / `self.*_counters` / `self.*_gauges` dicts, which are
  exported wholesale via `**` splats. Consumers are the module-level
  `*_KEYS` tuples (the router's `_PRESSURE_KEYS` pressure contract) and
  functions annotated `# metrics-consumer`, whose string-keyed `.get()` /
  subscript reads must name a produced key. Locally: a write to
  `self._x_stats["k"]` where `k` is not in the dict's initializer is
  counter drift — the increment lands in a key the export never shows
  until first hit, and usually means a renamed metric.

AR304 — `_GUARDED_BY` staleness. A registry entry `"Class.attr"` whose
  class IS defined in the module but whose attr is never touched by the
  class is a leftover from a refactor: it waives AR101 for an attribute
  that no longer exists (the unknown-lock and unknown-class halves are
  AR104's).

AR305 — config-knob drift. argparse flags in `launcher/` servers mirror
  dataclass fields in `api/cli_args.py`; a flag whose dest matches no
  field in the analyzed set has drifted from the knob it mirrors
  (`--tp-size` vs `tensor_parallel_size` is the canonical shape — fix
  with an explicit `dest=`). Flags that are genuinely launcher
  infrastructure (not config mirrors) carry `# knob: launcher-only`;
  `host`/`port` are built-in infra. The `/info` surface is checked the
  same way: `self.config.X` reads inside an `_info` handler must name a
  real field. Skipped when the analyzed set harvested no dataclass
  fields.

Scope: harvesting runs everywhere; the registration-side (dead endpoint),
argparse, and `/info` checks apply only to `launcher/` files — and to
paths containing `fixtures` (the seeded test fixtures), which are always
fully checked. Cross-file findings are pragma-suppressable at their
anchor site like every other rule.
"""

from __future__ import annotations

import ast
import fnmatch
import re
from dataclasses import dataclass, field

from areal_tpu.analysis.concurrency import _guard_registry
from areal_tpu.analysis.core import Finding, SourceFile, call_root

# single-segment endpoint path: "/generate", "/kv_recv" — NOT "/q" alone
# being excluded by shape ("/q" matches), so the call-context filter below
# is what keeps string-suffix literals like `endswith(("/q", "/scale"))`
# out of the client-ref set
_PATH_RE = re.compile(r"^/[a-z_][a-z0-9_]*$")

# callee leaf names that take an endpoint path argument; deliberately NOT
# generic verbs like `get` — `os.environ.get("TMPDIR", "/tmp")` is exactly
# the endpoint-shaped non-endpoint that would poison the pairing
_HTTP_CALLS = {
    "arequest_with_retry",
    "aget_with_retry",
    "wait_server_healthy",
    "_fanout",
    "_http_get",
    "_http_post",
    "http_get",
    "http_post",
}

_ROUTE_ADDERS = {
    "add_get",
    "add_post",
    "add_put",
    "add_delete",
    "add_patch",
    "add_route",
}

_SEAM_ENTRIES = {"fire", "afire", "tear"}

_STATS_SUFFIXES = ("_stats", "_counters", "_gauges")

_WIRE_EXTERNAL_RE = re.compile(r"#\s*wire:\s*external")
_METRICS_PRODUCER_RE = re.compile(r"#\s*metrics-producer")
_METRICS_CONSUMER_RE = re.compile(r"#\s*metrics-consumer")
_LAUNCHER_ONLY_RE = re.compile(r"#\s*knob:\s*launcher-only")

# argparse dests that are process plumbing on every server, never mirrors
_INFRA_DESTS = {"host", "port"}


def _scoped(display_path: str) -> bool:
    """Registration/argparse/_info checks: launcher servers + fixtures."""
    p = display_path.replace("\\", "/")
    return "launcher/" in p or "fixtures" in p


def _line_has(sf: SourceFile, line: int, rx: re.Pattern) -> bool:
    """The annotation is on the node's line or the preceding comment line
    (same placement contract as inline pragmas)."""
    for ln in (line, line - 1):
        if 0 < ln <= len(sf.lines) and rx.search(sf.lines[ln - 1]):
            if ln == line or sf.lines[ln - 1].strip().startswith("#"):
                return True
    return False


@dataclass
class _Site:
    file: str
    line: int


@dataclass
class WireState:
    """Cross-file accumulator for the AR3xx wire contracts."""

    # AR301
    routes: dict[str, list[tuple[_Site, bool, bool]]] = field(
        default_factory=dict
    )  # path -> [(site, in_scope, external)]
    client_refs: dict[str, list[_Site]] = field(default_factory=dict)
    # AR302
    seams: dict[str, dict[str, _Site]] = field(
        default_factory=dict
    )  # seam -> {module -> first site}
    patterns: list[tuple[str, _Site]] = field(default_factory=list)
    # AR303
    produced_keys: set[str] = field(default_factory=set)
    declared_keys: list[tuple[str, str, _Site]] = field(
        default_factory=list
    )  # (container, key, site) from *_KEYS tuples
    consumer_reads: list[tuple[str, str, _Site]] = field(
        default_factory=list
    )  # (fn qualname, key, site)
    # AR305
    dataclass_fields: set[str] = field(default_factory=set)
    argparse_flags: list[tuple[str, str, _Site]] = field(
        default_factory=list
    )  # (dest, flag, site)
    info_reads: list[tuple[str, _Site]] = field(default_factory=list)

    _files: dict[str, SourceFile] = field(default_factory=dict)

    def _suppressed(self, f: Finding) -> bool:
        sf = self._files.get(f.file)
        return sf.suppressed(f.rule, f.line) if sf else False

    def finalize(self) -> list[Finding]:
        out: list[Finding] = []

        def emit(rule: str, site: _Site, key: str, msg: str) -> None:
            f = Finding(
                rule=rule, file=site.file, line=site.line, key=key, message=msg
            )
            if not self._suppressed(f):
                out.append(f)

        # -- AR301: route pairing -------------------------------------
        if self.routes:  # a client-only sweep cannot judge pairing
            for path, sites in sorted(self.client_refs.items()):
                if path in self.routes:
                    continue
                for site in sites:
                    emit(
                        "AR301",
                        site,
                        path,
                        f"client references endpoint {path!r} but no "
                        "analyzed server registers it — the call can only "
                        "404",
                    )
            for path, regs in sorted(self.routes.items()):
                if path in self.client_refs:
                    continue
                for site, in_scope, external in regs:
                    if not in_scope or external:
                        continue
                    emit(
                        "AR301",
                        site,
                        path,
                        f"endpoint {path!r} is registered but no analyzed "
                        "client references it — dead route (annotate "
                        "`# wire: external` if it is an ops/bench surface)",
                    )

        # -- AR302: fault-seam validity -------------------------------
        if self.seams:  # a plan-only sweep cannot judge patterns
            for pat, site in self.patterns:
                if not any(fnmatch.fnmatch(s, pat) for s in self.seams):
                    emit(
                        "AR302",
                        site,
                        pat,
                        f"fault pattern {pat!r} matches no harvested seam "
                        "— this FaultPoint silently never fires",
                    )
        for seam, mods in sorted(self.seams.items()):
            if len(mods) > 1:
                first = min(mods.values(), key=lambda s: (s.file, s.line))
                emit(
                    "AR302",
                    first,
                    seam,
                    f"seam {seam!r} is fired from {len(mods)} modules "
                    f"({sorted(mods)}) — one fnmatch pattern now perturbs "
                    "two unrelated boundaries; rename one seam",
                )

        # -- AR303: metrics contract (cross-file halves) --------------
        if self.produced_keys:
            for container, key, site in self.declared_keys:
                if key not in self.produced_keys:
                    emit(
                        "AR303",
                        site,
                        f"{container}.{key}",
                        f"{container} declares metric key {key!r} but no "
                        "analyzed producer exports it — the poll reads a "
                        "key that is never there",
                    )
            for fn, key, site in self.consumer_reads:
                if key not in self.produced_keys:
                    emit(
                        "AR303",
                        site,
                        f"{fn}.{key}",
                        f"metrics consumer {fn}() reads key {key!r} but no "
                        "analyzed producer exports it",
                    )

        # -- AR305: config-knob drift ---------------------------------
        if self.dataclass_fields:
            for dest, flag, site in self.argparse_flags:
                if dest in self.dataclass_fields or dest in _INFRA_DESTS:
                    continue
                emit(
                    "AR305",
                    site,
                    dest,
                    f"argparse flag {flag!r} (dest {dest!r}) mirrors no "
                    "config dataclass field — renamed knob? use an "
                    "explicit dest= or annotate `# knob: launcher-only`",
                )
            for name, site in self.info_reads:
                if name not in self.dataclass_fields:
                    emit(
                        "AR305",
                        site,
                        f"info.{name}",
                        f"/info surface reads self.config.{name} but no "
                        "config dataclass declares that field",
                    )

        return out


def _const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _path_of(value: str) -> str | None:
    """Normalize a literal to an endpoint path (query string stripped)."""
    p = value.split("?", 1)[0]
    return p if _PATH_RE.match(p) else None


def _fstring_paths(node: ast.JoinedStr) -> list[str]:
    """Leading-constant path pieces of an f-string: `f"/kv_recv?xid={x}"`
    -> ["/kv_recv"], `f"http://{addr}/health"` -> ["/health"]."""
    out = []
    for piece in node.values:
        s = _const_str(piece)
        if s and s.startswith("/"):
            p = _path_of(s)
            if p:
                out.append(p)
    return out


class _Harvest(ast.NodeVisitor):
    def __init__(self, sf: SourceFile, state: WireState):
        self.sf = sf
        self.state = state
        self.scoped = _scoped(sf.display)
        self.module = sf.display
        self.stack: list[str] = []
        self.findings: list[Finding] = []
        # nearest enclosing metrics-producer / metrics-consumer function
        self._producer_depth = 0
        self._consumer: str | None = None
        self._info_depth = 0

    def _site(self, node: ast.AST) -> _Site:
        return _Site(self.sf.display, node.lineno)

    # -- class-local collection (AR303 stats drift, AR304) ------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.stack.append(node.name)
        self._check_stats_drift(node)
        self.generic_visit(node)
        self.stack.pop()

    def _check_stats_drift(self, cls: ast.ClassDef) -> None:
        inits: dict[str, set[str]] = {}
        for n in ast.walk(cls):
            if not (isinstance(n, ast.Assign) and len(n.targets) == 1):
                continue
            t = n.targets[0]
            if not (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
                and t.attr.endswith(_STATS_SUFFIXES)
            ):
                continue
            keys = _dict_keys(n.value)
            if keys is not None:
                inits.setdefault(t.attr, set()).update(keys)
                # the whole dict is exported via `**` splats in the
                # metrics handlers, so its keys count as produced
                self.state.produced_keys.update(keys)
        if not inits:
            return
        for n in ast.walk(cls):
            tgt = None
            if isinstance(n, (ast.Assign, ast.AugAssign)):
                targets = n.targets if isinstance(n, ast.Assign) else [n.target]
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        tgt = t
            if tgt is None:
                continue
            base = tgt.value
            if not (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
                and base.attr in inits
            ):
                continue
            key = _const_str(tgt.slice)
            if key is not None and key not in inits[base.attr]:
                self.findings.append(
                    Finding(
                        rule="AR303",
                        file=self.sf.display,
                        line=n.lineno,
                        key=f"{cls.name}.{base.attr}[{key}]",
                        message=(
                            f"self.{base.attr}[{key!r}] is mutated but the "
                            "initializer never declares that key — the "
                            "export misses it until first hit (renamed "
                            "metric?)"
                        ),
                    )
                )

    # -- functions: producer/consumer framing, argparse, _info --------

    def _visit_fn(self, node) -> None:
        self.stack.append(node.name)
        name = node.name
        # `_health` is a producer too: the router poll reads version/role
        # off the health body, so the health surface is part of the
        # contract the same way /metrics is
        produces = (
            name == "get_metrics"
            or name == "_health"
            or name.endswith("_metrics")
            or _line_has(self.sf, node.lineno, _METRICS_PRODUCER_RE)
        )
        consumes = _line_has(self.sf, node.lineno, _METRICS_CONSUMER_RE)
        is_info = self.scoped and name == "_info"
        if produces:
            self._producer_depth += 1
        if is_info:
            self._info_depth += 1
        prev_consumer = self._consumer
        if consumes:
            self._consumer = ".".join(self.stack)
        self.generic_visit(node)
        if produces:
            self._producer_depth -= 1
        if is_info:
            self._info_depth -= 1
        self._consumer = prev_consumer
        self.stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    # -- assignments: *_ENDPOINT, *_KEYS, dataclass fields ------------

    def visit_Assign(self, node: ast.Assign) -> None:
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            tname = node.targets[0].id
            if tname.endswith("_ENDPOINT"):
                s = _const_str(node.value)
                p = _path_of(s) if s else None
                if p:
                    self.state.client_refs.setdefault(p, []).append(
                        self._site(node)
                    )
            elif tname.endswith("_KEYS") and isinstance(
                node.value, (ast.Tuple, ast.List)
            ):
                for el in node.value.elts:
                    s = _const_str(el)
                    if s is not None:
                        self.state.declared_keys.append(
                            (tname, s, _Site(self.sf.display, el.lineno))
                        )
        self._maybe_record_produced(node)
        self._maybe_statement_producer(node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._maybe_statement_producer(node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._maybe_record_produced(node)
        self.generic_visit(node)

    def _maybe_statement_producer(self, node) -> None:
        """`# metrics-producer` on an assignment: every dict key inside
        the value is produced — for entry templates that ride inside a
        metrics body without being built in a producer function (the
        router's breaker defaultdict lambda)."""
        if node.value is None or not _line_has(
            self.sf, node.lineno, _METRICS_PRODUCER_RE
        ):
            return
        for n in ast.walk(node.value):
            keys = _dict_keys(n)
            if keys:
                self.state.produced_keys.update(keys)

    def _maybe_record_produced(self, node) -> None:
        """Inside a metrics producer, `out["k"] = ...` produces "k"."""
        if not self._producer_depth:
            return
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for t in targets:
            if isinstance(t, ast.Subscript):
                key = _const_str(t.slice)
                if key is not None:
                    self.state.produced_keys.add(key)

    # -- dict literals inside producers -------------------------------

    def visit_Dict(self, node: ast.Dict) -> None:
        if self._producer_depth:
            for k in node.keys:
                s = _const_str(k) if k is not None else None
                if s is not None:
                    self.state.produced_keys.add(s)
        # FaultPlan.from_json-style embedded plans: {"site": "<pattern>"}
        for k, v in zip(node.keys, node.values):
            if k is not None and _const_str(k) == "site":
                s = _const_str(v)
                if s:
                    self.state.patterns.append((s, _Site(self.sf.display, v.lineno)))
        self.generic_visit(node)

    # -- calls: routes, HTTP refs, seams, FaultPoint, argparse, dict() --

    def visit_Call(self, node: ast.Call) -> None:
        name = call_root(node) or ""
        leaf = name.rsplit(".", 1)[-1]

        if leaf in _ROUTE_ADDERS:
            for a in node.args:
                s = _const_str(a)
                if s and s.startswith("/"):
                    p = _path_of(s)
                    if p:
                        external = _line_has(
                            self.sf, node.lineno, _WIRE_EXTERNAL_RE
                        )
                        self.state.routes.setdefault(p, []).append(
                            (self._site(node), self.scoped, external)
                        )
                    break

        elif leaf in _HTTP_CALLS:
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                s = _const_str(a)
                if s is not None:
                    p = _path_of(s)
                    if p:
                        self.state.client_refs.setdefault(p, []).append(
                            _Site(self.sf.display, a.lineno)
                        )
                elif isinstance(a, ast.JoinedStr):
                    for p in _fstring_paths(a):
                        self.state.client_refs.setdefault(p, []).append(
                            _Site(self.sf.display, a.lineno)
                        )

        if leaf in _SEAM_ENTRIES and node.args:
            s = _const_str(node.args[0])
            if s:
                self.state.seams.setdefault(s, {}).setdefault(
                    self.module, _Site(self.sf.display, node.lineno)
                )

        if leaf == "FaultPoint":
            pat = None
            pnode = None
            if node.args:
                pat = _const_str(node.args[0])
                pnode = node.args[0]
            for kw in node.keywords:
                if kw.arg == "site":
                    pat = _const_str(kw.value)
                    pnode = kw.value
            if pat and pnode is not None:
                self.state.patterns.append(
                    (pat, _Site(self.sf.display, pnode.lineno))
                )

        if leaf == "dict" and self._producer_depth:
            for kw in node.keywords:
                if kw.arg is not None:
                    self.state.produced_keys.add(kw.arg)

        if leaf == "add_argument" and self.scoped and node.args:
            flag = _const_str(node.args[0])
            if (
                flag
                and flag.startswith("--")
                and not _line_has(self.sf, node.lineno, _LAUNCHER_ONLY_RE)
            ):
                dest = flag[2:].replace("-", "_")
                for kw in node.keywords:
                    if kw.arg == "dest":
                        d = _const_str(kw.value)
                        if d:
                            dest = d
                self.state.argparse_flags.append(
                    (dest, flag, self._site(node))
                )

        if self._consumer and leaf == "get" and node.args:
            s = _const_str(node.args[0])
            if s is not None:
                self.state.consumer_reads.append(
                    (self._consumer, s, self._site(node))
                )

        self.generic_visit(node)

    # -- subscripts: consumer reads -----------------------------------

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if self._consumer and isinstance(node.ctx, ast.Load):
            s = _const_str(node.slice)
            if s is not None:
                self.state.consumer_reads.append(
                    (self._consumer, s, self._site(node))
                )
        self.generic_visit(node)

    # -- attribute reads: /info surface -------------------------------

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if self._info_depth:
            v = node.value
            if (
                isinstance(v, ast.Attribute)
                and v.attr == "config"
                and isinstance(v.value, ast.Name)
                and v.value.id == "self"
            ):
                self.state.info_reads.append((node.attr, self._site(node)))
        self.generic_visit(node)


def _dict_keys(value: ast.AST) -> set[str] | None:
    """String keys of a `{...}` or `dict(k=...)` initializer literal."""
    if isinstance(value, ast.Dict):
        out = set()
        for k in value.keys:
            s = _const_str(k) if k is not None else None
            if s is not None:
                out.add(s)
        return out
    if (
        isinstance(value, ast.Call)
        and (call_root(value) or "").rsplit(".", 1)[-1] == "dict"
    ):
        return {kw.arg for kw in value.keywords if kw.arg is not None}
    return None


def _dataclass_fields(tree: ast.Module) -> set[str]:
    out: set[str] = set()
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        is_dc = False
        for dec in node.decorator_list:
            d = dec.func if isinstance(dec, ast.Call) else dec
            dname = None
            if isinstance(d, ast.Name):
                dname = d.id
            elif isinstance(d, ast.Attribute):
                dname = d.attr
            if dname == "dataclass":
                is_dc = True
        if not is_dc:
            continue
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                out.add(stmt.target.id)
    return out


def _check_registry_staleness(sf: SourceFile) -> list[Finding]:
    """AR304: `_GUARDED_BY["Class.attr"]` where the class exists in this
    module but never touches `self.attr` — a refactor leftover waiving
    AR101 for nothing."""
    registry, lines = _guard_registry(sf.tree)
    if not registry:
        return []
    classes = {
        n.name: n for n in sf.tree.body if isinstance(n, ast.ClassDef)
    }
    attrs: dict[str, set[str]] = {}
    findings: list[Finding] = []
    for key in sorted(registry):
        cls_name, _, attr = key.partition(".")
        cls = classes.get(cls_name)
        if cls is None or not attr:
            continue  # unknown class is AR104's finding
        if cls_name not in attrs:
            got: set[str] = set()
            for n in ast.walk(cls):
                if (
                    isinstance(n, ast.Attribute)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "self"
                ):
                    got.add(n.attr)
            attrs[cls_name] = got
        if attr not in attrs[cls_name]:
            findings.append(
                Finding(
                    rule="AR304",
                    file=sf.display,
                    line=lines.get(key, 1),
                    key=key,
                    message=(
                        f"_GUARDED_BY entry {key!r} names an attribute "
                        f"{cls_name} never touches — stale after a "
                        "refactor; remove the entry"
                    ),
                )
            )
    return findings


def analyze_wire(sf: SourceFile, state: WireState) -> list[Finding]:
    state._files[sf.display] = sf
    state.dataclass_fields |= _dataclass_fields(sf.tree)
    h = _Harvest(sf, state)
    h.visit(sf.tree)
    return h.findings + _check_registry_staleness(sf)
