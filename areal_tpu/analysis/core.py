"""areal-lint core: findings, pragmas, baseline, and the analysis driver.

The analyzers are pure-AST (no jax import, no code execution) so the suite
runs in milliseconds over the whole tree and can gate tier-1. Rule families:

  AR1xx — concurrency invariants (analysis/concurrency.py)
  AR2xx — JAX hot-path hazards  (analysis/jax_rules.py)
  AR3xx — cross-component wire contracts & observability (analysis/wire.py)

Suppression surfaces, in priority order:
  1. inline pragma      `# areal-lint: disable=AR101[,AR203]` on the flagged
     line or the immediately preceding (comment-only) line
  2. file pragma        `# areal-lint: disable-file=AR201` anywhere at module
     top level (first 30 lines)
  3. baseline file      JSON entries keyed on (file, rule, key) — `key` is a
     rule-specific *stable* identifier (attribute / symbol name), not a line
     number, so baselines survive unrelated edits. Every entry carries a
     one-line `justification`.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field


@dataclass(frozen=True)
class Finding:
    rule: str  # "AR101"
    file: str  # path as passed to the analyzer (normalized, /-separated)
    line: int  # 1-based
    key: str  # stable identifier used for baseline matching
    message: str

    def format(self) -> str:
        return f"{self.file}:{self.line}: {self.rule} [{self.key}] {self.message}"


RULES: dict[str, str] = {
    "AR101": "shared attribute written from multiple thread contexts "
    "without a declared guard",
    "AR102": "lock acquisition-order cycle",
    "AR103": "lock acquired against the declared rank order",
    "AR104": "guarded-by annotation names an undeclared lock",
    "AR201": "implicit device->host sync inside a loop "
    "(.item() / float() / int() / np.asarray on a device array)",
    "AR202": "use of a buffer after it was donated to a jit call",
    "AR203": "jnp.asarray upload aliasing a host array that is later "
    "mutated in place",
    "AR204": "retrace hazard: loop-varying Python scalar or unhashable "
    "argument to a jit-compiled function",
    "AR106": "broad except swallows the failure without logging, "
    "re-raising, or preserving the exception",
    "AR301": "HTTP route pairing: client path with no registration, or "
    "registered endpoint no client reaches",
    "AR302": "fault-seam validity: plan pattern matching no real seam, "
    "or one seam name fired from two modules",
    "AR303": "metrics contract drift between producers (get_metrics / "
    "/metrics) and consumers (poll keys, counters)",
    "AR304": "_GUARDED_BY registry entry naming an attribute the class "
    "no longer has",
    "AR305": "config-knob drift: argparse flag or /info field that "
    "mirrors no config dataclass field",
}

_PRAGMA_RE = re.compile(r"#\s*areal-lint:\s*disable=([A-Z0-9,\s]+)")
_FILE_PRAGMA_RE = re.compile(r"#\s*areal-lint:\s*disable-file=([A-Z0-9,\s]+)")
GUARDED_BY_RE = re.compile(r"guarded-by:\s*([A-Za-z_][\w.]*)")


def _parse_rule_list(blob: str) -> set[str]:
    return {r.strip() for r in blob.split(",") if r.strip()}


class SourceFile:
    """One parsed module: tree + raw lines + pragma index."""

    def __init__(self, path: str, display_path: str | None = None):
        self.path = path
        self.display = (display_path or path).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=path)
        self._line_pragmas: dict[int, set[str]] = {}
        self._file_pragmas: set[str] = set()
        for i, ln in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(ln)
            if m:
                self._line_pragmas[i] = _parse_rule_list(m.group(1))
            if i <= 30:
                m = _FILE_PRAGMA_RE.search(ln)
                if m:
                    self._file_pragmas |= _parse_rule_list(m.group(1))

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self._file_pragmas:
            return True
        for ln in (line, line - 1):
            rules = self._line_pragmas.get(ln)
            # a pragma on the preceding line only counts if that line is
            # comment-only — otherwise it belongs to that line's own code
            if rules and rule in rules:
                if ln == line:
                    return True
                prev = self.lines[ln - 1].strip() if 0 < ln <= len(self.lines) else ""
                if prev.startswith("#"):
                    return True
        return False


_PLACEHOLDER_JUSTIFICATION = "TODO: justify or fix"


@dataclass
class Baseline:
    """Checked-in list of accepted findings (false positives, justified)."""

    entries: list[dict] = field(default_factory=list)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        return cls(entries=list(data.get("entries", [])))

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(
                {"version": 1, "entries": self.entries},
                f,
                indent=2,
                sort_keys=False,
            )
            f.write("\n")

    @staticmethod
    def _file_match(finding_file: str, entry_file: str) -> bool:
        # baseline files are repo-relative; findings may carry absolute
        # paths depending on how the analyzer was invoked
        return finding_file == entry_file or finding_file.endswith(
            "/" + entry_file
        )

    def covers(self, f: Finding) -> bool:
        return any(
            e.get("rule") == f.rule
            and e.get("key") == f.key
            and self._file_match(f.file, e.get("file", ""))
            for e in self.entries
        )

    def invalid(self) -> list[dict]:
        """Entries whose justification was never written: missing, empty /
        whitespace-only, or still the `--write-baseline` placeholder. The
        baseline contract is one honest sentence per accepted finding — a
        placeholder silently waives the rule without the review the
        justification field exists to force, so these are surfaced through
        the same reporting channel as stale entries."""
        out = []
        for e in self.entries:
            j = e.get("justification")
            if (
                j is None
                or not str(j).strip()
                or str(j).strip() == _PLACEHOLDER_JUSTIFICATION
            ):
                out.append(e)
        return out

    def unused(self, findings: list[Finding]) -> list[dict]:
        return [
            e
            for e in self.entries
            if not any(
                e.get("rule") == f.rule
                and e.get("key") == f.key
                and self._file_match(f.file, e.get("file", ""))
                for f in findings
            )
        ]

    @classmethod
    def from_findings(cls, findings: list[Finding]) -> "Baseline":
        return cls(
            entries=[
                {
                    "file": f.file,
                    "rule": f.rule,
                    "key": f.key,
                    "justification": _PLACEHOLDER_JUSTIFICATION,
                }
                for f in sorted(findings, key=lambda x: (x.file, x.rule, x.key))
            ]
        )


def iter_py_files(paths: list[str]) -> list[tuple[str, str]]:
    """Expand files/directories into (abs_path, display_path) pairs."""
    out: list[tuple[str, str]] = []
    for p in paths:
        if os.path.isfile(p):
            out.append((p, p))
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(
                d for d in dirs if d not in ("__pycache__", ".git", "node_modules")
            )
            for fn in sorted(files):
                if fn.endswith(".py"):
                    full = os.path.join(root, fn)
                    out.append((full, full))
    return out


def analyze_paths(
    paths: list[str],
    rules: set[str] | None = None,
    collect_errors: list | None = None,
) -> list[Finding]:
    """Run every analyzer over the given files/dirs; pragma-filtered,
    baseline NOT applied (the caller decides)."""
    from areal_tpu.analysis.concurrency import (
        ConcurrencyState,
        analyze_concurrency,
    )
    from areal_tpu.analysis.jax_rules import analyze_jax
    from areal_tpu.analysis.robustness import analyze_robustness
    from areal_tpu.analysis.wire import WireState, analyze_wire

    state = ConcurrencyState()
    wire_state = WireState()
    findings: list[Finding] = []
    for full, display in iter_py_files(paths):
        try:
            sf = SourceFile(full, display)
        except (SyntaxError, UnicodeDecodeError) as e:
            if collect_errors is not None:
                collect_errors.append((display, repr(e)))
            continue
        per_file = (
            analyze_concurrency(sf, state)
            + analyze_jax(sf)
            + analyze_robustness(sf)
            + analyze_wire(sf, wire_state)
        )
        for f in per_file:
            if rules is not None and f.rule not in rules:
                continue
            if sf.suppressed(f.rule, f.line):
                continue
            findings.append(f)
    # cross-file findings (AR102/AR103 lock order, AR3xx wire contracts);
    # pragma suppression is applied inside finalize via the retained
    # SourceFiles
    for f in state.finalize() + wire_state.finalize():
        if rules is None or f.rule in rules:
            findings.append(f)
    findings.sort(key=lambda f: (f.file, f.line, f.rule, f.key))
    return findings


# -- small shared AST helpers ------------------------------------------------


def dotted_name(node: ast.AST) -> str | None:
    """`a.b.c` -> "a.b.c", Name -> "a"; None for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_root(call: ast.Call) -> str | None:
    """Dotted name of a call's callee ("jnp.asarray", "self._fn")."""
    return dotted_name(call.func)
