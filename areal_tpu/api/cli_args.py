"""Experiment configuration tree + CLI/YAML loader.

Parity target: areal/api/cli_args.py (~35 dataclasses, OmegaConf merge,
`--config file.yaml key=value` overrides). Field names are kept identical to
the reference wherever the concept carries over (GenerationHyperparameters,
OptimizerConfig, TrainEngineConfig, PPOActorConfig incl. `use_decoupled_loss`,
`recompute_logprob`, `max_head_offpolicyness`, `group_size`,
`dynamic_sampling`, SaverConfig, …) so that reference configs port with only
backend-name changes. CUDA-server configs (SGLangConfig/vLLMConfig) are
replaced by `JaxDecodeConfig` — the TPU-native decode engine.
"""

from __future__ import annotations

import argparse
import dataclasses
import getpass
import os
from dataclasses import dataclass, field

import yaml

from areal_tpu.utils import structured
from areal_tpu.utils.name_resolve import NameResolveConfig

__all__ = [
    "NormConfig",
    "MicroBatchSpec",
    "GenerationHyperparameters",
    "OptimizerConfig",
    "JaxEngineConfig",
    "TrainEngineConfig",
    "PPOActorConfig",
    "PPOCriticConfig",
    "JaxDecodeConfig",
    "InferenceEngineConfig",
    "SaverConfig",
    "EvaluatorConfig",
    "RecoverConfig",
    "WandBConfig",
    "SwanlabConfig",
    "TensorBoardConfig",
    "StatsLoggerConfig",
    "NameResolveConfig",
    "ClusterSpecConfig",
    "DatasetConfig",
    "LauncherConfig",
    "SlurmLauncherConfig",
    "BaseExperimentConfig",
    "SFTConfig",
    "RWConfig",
    "GRPOConfig",
    "PPOConfig",
    "parse_cli_args",
    "load_expr_config",
    "save_config",
]


@dataclass
class NormConfig:
    """Normalization spec for rewards/advantages (reference cli_args.py:22)."""

    mean_level: str | None = "batch"  # "batch" | "group" | None
    mean_leave1out: bool = False
    std_level: str | None = "batch"  # "batch" | "group" | None
    std_unbiased: bool = False
    eps: float = 1e-5
    group_size: int = 1


@dataclass
class MicroBatchSpec:
    """Micro-batch splitting spec (reference cli_args.py:61)."""

    n_mbs: int | None = 1
    granularity: int = 1
    max_tokens_per_mb: int | None = None


@dataclass
class GenerationHyperparameters:
    """Sampling hyperparameters (reference cli_args.py:96)."""

    n_samples: int = 1
    max_new_tokens: int = 16384
    min_new_tokens: int = 0
    max_tokens: int | None = None
    greedy: bool = False
    top_p: float = 1.0
    top_k: int = int(1e8)
    temperature: float = 1.0
    stop_token_ids: list[int] = field(default_factory=list)
    stop: list[str] | None = None
    frequency_penalty: float = 0.0

    def new(self, **kwargs) -> "GenerationHyperparameters":
        out = dataclasses.replace(self)
        for k, v in kwargs.items():
            setattr(out, k, v)
        return out


@dataclass
class OptimizerConfig:
    """Optax optimizer + schedule spec (reference cli_args.py:160).

    `type` supports "adamw" (AnyPrecision-equivalent: bf16 params, fp32
    moments by default) and "sgd"; schedules: cosine/linear/constant with
    linear warmup.
    """

    type: str = "adamw"
    lr: float = 2e-5
    weight_decay: float = 0.05
    beta1: float = 0.9
    beta2: float = 0.95
    eps: float = 1e-5
    min_lr_ratio: float = 0.0
    lr_scheduler_type: str = "constant"  # "cosine" | "linear" | "constant"
    warmup_steps_proportion: float = 0.001
    offload: bool = False
    gradient_clipping: float = 1.0
    # dtype of Adam moments; fp32 is the AnyPrecisionAdamW default.
    moment_dtype: str = "float32"


@dataclass
class JaxEngineConfig:
    """TPU/GSPMD engine knobs (replaces FSDPEngineConfig/MegatronEngineConfig).

    The reference's FSDP2 wrap policy and Megatron DDP flags have no TPU
    analogue: parameter sharding is a NamedSharding over the mesh's
    ("fsdp",) axis; rematerialisation replaces activation checkpointing.
    """

    # Which mesh axes shard parameters ZeRO-style; () replicates.
    fsdp_axes: list[str] = field(default_factory=lambda: ["fsdp"])
    # jax.checkpoint policy: "none" | "full" | "dots_saveable" |
    # "dots_with_no_batch_dims_saveable"
    remat_policy: str = "full"
    # Fused LM-head loss: apply the head + cross-entropy in vocab chunks
    # (ops/fused_xent.py) so the f32 [tokens, vocab] logits never
    # materialize — lifts the micro-batch HBM ceiling the dense path hits
    # on wide-vocab models. Exact to f32 roundoff; disable to force the
    # dense logits path.
    fused_lm_loss: bool = True
    # Use scan-over-layers for fast compiles and PP-friendly stacking.
    scan_layers: bool = True
    # Offload optimizer state to host memory (jax.device_put w/ host sharding).
    offload_params: bool = False
    # Pipeline schedule under pp>1: "1f1b" interleaves each micro-batch's
    # backward right behind its forward (live activation stash capped at
    # 2*pp-1 per stage, so bigger M — smaller bubble — fits in fixed HBM);
    # "1f1b_interleaved" additionally splits each rank into
    # `virtual_pp_size` non-contiguous virtual stages (Megatron's
    # interleaved schedule), shrinking the bubble ~1/v at a stash bound of
    # v*(2*pp-1); "gpipe" is the all-forward-then-all-backward
    # reference/fallback path.
    pipeline_schedule: str = "1f1b"
    # Virtual pipeline stages per pp rank (interleaved 1F1B). 1 = one
    # contiguous stage per rank. Values > 1 require
    # pipeline_schedule "1f1b_interleaved" or "gpipe" and
    # num_hidden_layers % (pp * virtual_pp_size) == 0; the engine then
    # stores the scanned layer stack in chunk-major order (layer
    # round-robin across ranks) so chunk dispatch is a pure reshape.
    virtual_pp_size: int = 1
    # ZeRO-1: shard AdamW moments and the optimizer update over the dp
    # axis (reduce-scatter grads -> sharded update -> all-gather params,
    # expressed as shardings so XLA emits the collectives). Frees
    # 8 bytes/param of replicated fp32 moment state per dp rank; bitwise
    # identical to the replicated update (reduction order unchanged —
    # sharding only partitions the elementwise moment math).
    zero1_optimizer: bool = True
    # Hybrid ICI/DCN mesh: number of accelerator slices (pods) the trainer
    # spans. 1 = single-slice mesh (plain build_mesh). > 1 places the axes
    # named in mesh_dcn_axes across slice boundaries so only their traffic
    # (the pp stage-boundary activation hop, the dp gradient reduce)
    # crosses the slower DCN; axis order inside a slice is unchanged.
    mesh_num_slices: int = 1
    # Which mesh axes cross slice boundaries when mesh_num_slices > 1, in
    # mesh order. Product of their DCN factors must equal mesh_num_slices;
    # "pp" (outermost, least traffic) is the default, optionally with an
    # outer "dp" split.
    mesh_dcn_axes: list[str] = field(default_factory=lambda: ["pp"])
    # Zig-zag context-parallel layout: shard the packed token axis as paired
    # chunks (i, 2n-1-i) so every ring-attention shard does equal causal
    # work. Exact (a pure relabeling, inverted on outputs); applies only
    # when attention resolves to the ring path.
    cp_zigzag: bool = True


@dataclass
class TrainEngineConfig:
    """Train engine contract config (reference cli_args.py:315)."""

    experiment_name: str = ""
    trial_name: str = ""
    path: str = ""  # HF model path or local checkpoint dir
    # "auto" | "pallas" (flash kernel) | "xla" (dense mask) | "chunked"
    # (XLA online-softmax over KV chunks — the O(T)-memory path sliding-
    # window models resolve to) | "ring" (context-parallel)
    attn_impl: str = "auto"
    init_from_scratch: bool = False
    is_critic: bool = False
    mb_spec: MicroBatchSpec = field(default_factory=MicroBatchSpec)
    pad_to_maximum: bool = False
    disable_dropout: bool = True
    gradient_checkpointing: bool = True
    dtype: str = "bfloat16"
    # dtype of the cross-micro-batch gradient accumulator. It is SHARDED
    # like the parameters (fsdp over dp), so its per-chip HBM cost is
    # params_per_chip * 4 bytes at fp32 — e.g. 7B over 8 chips ≈ 3.5 GB/chip
    # fp32, halved by "bfloat16" at the cost of accumulation precision
    # across micro-batches (the within-backward matmul accumulation stays
    # fp32 either way). The reference's Megatron fuses accumulation into
    # backward buffers; GSPMD's equivalent lever is this dtype knob.
    # Irrelevant under pp>1 (one backward, no explicit accumulator).
    grad_reduce_dtype: str = "float32"
    optimizer: OptimizerConfig | None = None
    weight_update_mode: str = "memory"  # "memory" (device_put) | "disk"
    # LoRA delta push: when LoRA is active, the "dcn" weight push ships only
    # the trainable adapter subtree (A/B matrices) and the decode servers
    # fold the delta into their pristine base kernels at commit — wire bytes
    # drop by orders of magnitude vs. pushing merged full kernels. Disable
    # to force the full merged-tree push (e.g. decode servers that did not
    # start from the same base checkpoint).
    weight_sync_delta: bool = True
    backend: str = "jax"
    jax: JaxEngineConfig = field(default_factory=JaxEngineConfig)
    use_lora: bool = False
    lora_rank: int = 32
    lora_alpha: int = 16
    target_modules: list[str] = field(default_factory=list)


@dataclass
class PPOActorConfig(TrainEngineConfig):
    """PPO/GRPO actor config (reference cli_args.py:390)."""

    group_size: int = 1
    ppo_n_minibatches: int = 4
    eps_clip: float = 0.2
    eps_clip_higher: float | None = None
    c_clip: float | None = None
    temperature: float = 1.0
    # reward shaping
    reward_norm: NormConfig | None = None
    reward_scaling: float = 1.0
    reward_bias: float = 0.0
    reward_clip: float = 20.0
    overlong_reward_penalty: bool = False
    overlong_tokens: int | None = None
    overlong_penalty_factor: float | None = None
    mask_no_eos_with_zero: bool = False
    # advantage estimation
    discount: float = 1.0
    gae_lambda: float = 1.0
    adv_norm: NormConfig | None = None
    # KL regularization
    kl_ctl: float = 0.1
    kl_estimator: str = "k1"  # "k1" | "k2" | "k3"
    # asynchronous / decoupled-PPO controls
    recompute_logprob: bool = False
    use_decoupled_loss: bool = False
    behav_imp_weight_cap: float | None = None
    dynamic_sampling: bool = False
    log_agent_stats: bool = False
    log_agent_stats_keys: list[str] = field(default_factory=list)
    max_new_tokens: int = 1024
    # AEnt clamped-entropy regularization (parity: recipe/AEnt/aent_args.py).
    # entropy_coeff > 0 adds an entropy bonus to the GRPO loss;
    # entropy_clamp > 0 excludes that fraction of the vocab (lowest logits)
    # from the bonus so it can't reward mass on the garbage tail.
    entropy_coeff: float = 0.0
    entropy_clamp: float = 0.0
    # adaptive coefficient: nudge entropy_coeff to keep measured entropy
    # inside [entropy_low, entropy_high], clipped to the box bounds
    adaptive_entropy_coeff: bool = False
    entropy_high: float = 0.5
    entropy_low: float = 0.1
    entropy_coeff_lr: float = 0.001
    entropy_coeff_box_high: float = 0.01
    entropy_coeff_box_low: float = 1e-5
    entropy_warmup_steps: int = 0


@dataclass
class PPOCriticConfig(TrainEngineConfig):
    """PPO critic config (reference cli_args.py:513)."""

    ppo_n_minibatches: int = 4
    eps_clip: float = 0.5
    mask_no_eos_with_zero: bool = False


@dataclass
class JaxDecodeConfig:
    """TPU-native decode engine config (replaces SGLangConfig/vLLMConfig).

    Continuous batching over a static [max_running_requests, pages] KV layout
    so XLA compiles once; paged KV cache with prefix reuse; interruptible
    generation via chunked decode loops.
    """

    model_path: str = ""
    random_seed: int = 1
    dtype: str = "bfloat16"
    kv_cache_dtype: str = "bfloat16"
    # Paged-pool storage scheme (parity surface: SGLang's fp8/int8 KV
    # cache serving):
    #   "fp" (default): the pool stores kv_cache_dtype verbatim — the
    #     pre-quantization behavior, bit for bit, and the numerics oracle
    #     int8 drift is measured against.
    #   "int8": the pool stores int8 with per-(row, kv-head) f32 scales
    #     (ops/kv_quant.py; requires kv_layout="paged"). Rows are
    #     quantized ONCE at the decode/verify/prefill scatters and
    #     dequantized inside the paged-attention kernels right after each
    #     block's HBM→VMEM DMA — the same MB of pool holds ~2x the
    #     sessions, and every byte-moving path (host-tier swaps, session
    #     export/import, /drain migration) ships the quantized blocks +
    #     scales as-is, halving swap and wire bytes too. Mixed-dtype
    #     fleets reject migrated sessions as tombstoned honest misses
    #     (kv_migrate_dtype_rejects_total), like the weight-version rule.
    #     Drift (logprob delta, spec accept-rate shift) is measured by
    #     `bench.py --mode kvquant`, not assumed zero.
    kv_dtype: str = "fp"  # "fp" | "int8"
    # Weight serving dtype for the dense transformer matmul kernels
    # (models/qwen2.py q/k/v/o + dense mlp; MoE, embed, lm_head, norms,
    # biases and LoRA adapters always stay fp):
    #   "fp" (default): kernels stored and served in `dtype` — the
    #     pre-quantization behavior, bit for bit, and the numerics oracle
    #     int8 drift is measured against.
    #   "int8": kernels stored as per-output-channel symmetric absmax
    #     int8 + f32 scales (ops/quant.py). Quantized ONCE at the push
    #     producer (the trainer keeps fp32 masters; engine/jax_engine.py
    #     ships `.../q` + `.../scale` leaves over DCN, halving wire bytes
    #     and the commit pause) or locally on full-tree installs, and
    #     dequantized inside the fused dequant-matmul
    #     (ops/quant_matmul.py) right after each weight tile's HBM→VMEM
    #     DMA — decode chunks read half the weight bytes and the freed
    #     HBM goes to the KV pool (utils/hbm.py prices it). Drift vs the
    #     fp oracle is measured by `bench.py --mode wquant`, not assumed
    #     zero. The LoRA delta push stays fp and requantizes the folded
    #     kernels at install.
    weight_dtype: str = "fp"  # "fp" | "int8"
    # Replica role in a disaggregated fleet (launcher/decode_server.py):
    #   "unified" (default): one replica does both prefill and decode.
    #   "prefill": compute-bound role — runs prompt prefills only (via
    #     /prefill), parks the resulting KV, and streams it to a decode
    #     replica over the KV wire format (core/weight_transfer.py
    #     pack_kv_session) so long prefills never stall resident decode
    #     slots on the decode replicas.
    #   "decode": memory-bound role — imports migrated KV sessions into
    #     its host tier and resumes them through the host-tier promotion
    #     path (zero re-prefill). Any role still serves every endpoint
    #     (a prefill replica CAN decode) — the role steers the router and
    #     sizes defaults, it does not forbid traffic, so a degraded fleet
    #     keeps working.
    role: str = "unified"  # "unified" | "prefill" | "decode"
    # Frame size for migrated KV sessions (MiB per HTTP body on the
    # /kv_recv wire — same bounded-bucket rule as weight_chunked_mem_mb).
    kv_migrate_chunk_mb: float = 64.0
    # Host-tier budget a decode-role replica creates LAZILY (MiB) when it
    # receives a KV migration while kv_host_pool_mb == 0 — imported
    # sessions need a host tier to land in; this bounds it. Ignored when
    # kv_host_pool_mb already enabled the tier.
    kv_import_pool_mb: float = 256.0
    # Gen-side tensor parallelism: params + KV cache are sharded over a
    # [1,1,1,tp] decode mesh (parity: the server-side d/t/p dims of the
    # reference's allocation grammar, areal/api/alloc_mode.py:277-280 — dp
    # maps to independent server replicas, tp to this).
    tensor_parallel_size: int = 1
    context_length: int = 32768
    max_running_requests: int = 64
    page_size: int = 128  # tokens per KV page (TPU-friendly multiple of 128)
    # Paged-KV pool budget in tokens (x num_layers x kv heads). None =
    # full provisioning (max_running_requests x context_length — the dense
    # worst case). Setting it smaller is the point of paging: N concurrent
    # 32k-context slots only consume blocks for the tokens they actually
    # hold, with parked-KV eviction / donor-registry drop / active-slot
    # preemption (internal requeue) when the pool runs dry.
    kv_pool_tokens: int | None = None
    # Host-RAM tier under the paged pool (MiB; 0 disables — eviction then
    # DROPS parked/preempted KV and the resume re-prefills, exactly the
    # pre-tier behavior). When enabled, the eviction paths offload the
    # victim slot's blocks to a budgeted pinned host store
    # (engine/kv_pool.py HostKVStore, its own LRU) via async
    # device→host copies, and a resume promotes them back — fresh device
    # blocks + async upload — instead of re-running prefill. Turns
    # kv_pool_tokens from a hard capacity wall into a working-set knob;
    # resumed token/logprob streams are bit-identical to never-evicted
    # ones (the restored bytes ARE the original KV, and the slot's
    # sampling base key travels with the entry).
    kv_host_pool_mb: float = 0.0
    # How decode attention reaches the paged pool:
    #   "paged" (default): attend IN PLACE over the pool through the block
    #     table (ops/paged_attention.py) with an O(1) per-token cache
    #     write — no per-chunk gather/scatter of the active KV.
    #   "workspace": the legacy layout — gather each slot's blocks into a
    #     contiguous workspace, scan the chunk, scatter back (two HBM
    #     copies of the active KV per chunk). Kept as the numerics oracle;
    #     tokens/logprobs are identical between the two layouts.
    kv_layout: str = "paged"
    # Kernel for the in-pool attention read: "pallas" (TPU split-KV
    # flash-decode kernel; requires page_size % 128 == 0), "xla"
    # (gather-per-block fallback, bitwise-equal to the workspace path),
    # or "auto" (pallas on TPU, xla elsewhere).
    paged_attn_impl: str = "auto"
    hbm_utilization: float = 0.85
    max_prefill_tokens: int = 8192
    # tokens generated per decode-loop dispatch; interrupts land on chunk
    # boundaries (parity: partial rollout `new_tokens_per_chunk`)
    new_tokens_per_chunk: int = 128
    # Run-ahead decode scheduling: how many chunks the scheduler may keep
    # dispatched on the device while the host consumes the previous
    # chunk's results (stop-string scan, retire, admission, prefill
    # planning all overlap the in-flight chunk; per-slot sampling keys
    # keep the output bit-identical to the synchronous schedule). 0
    # restores the legacy dispatch-then-block loop. A slot the host
    # retires mid-run-ahead has its speculative tokens discarded and its
    # KV length rewound at the next dispatch.
    decode_runahead_chunks: int = 1
    # Draft-free speculative decoding. "ngram": a host-side prompt-lookup
    # drafter matches the trailing n-gram of each slot's (prompt +
    # generated) context against its own earlier tokens and proposes up
    # to spec_k continuation tokens; the device chunk becomes a VERIFY
    # chunk that scores all draft positions in one forward over the paged
    # pool and accepts the longest prefix matching what greedy/sampling
    # would have emitted, plus the model's own bonus token. Accepted
    # streams and logprobs are bit-identical to spec_decode="off"
    # (fold_in(base_key, position) sampling keys are a pure function of
    # token index); rejected draft rows are dead KV overwritten by the
    # next write. Strong on math/code rollouts that quote their prompts
    # (and on greedy repetition); draftless passes fall back to normal
    # chunks, so non-repetitive workloads keep baseline throughput.
    spec_decode: str = "off"  # "off" | "ngram"
    # max draft tokens proposed (and verified) per chunk per slot; the
    # verify q-width is bucketed to powers of two up to spec_k + 1
    spec_k: int = 4
    # longest trailing n-gram matched against the slot's earlier context
    # (matching tries spec_ngram_max down to 1, longest match wins)
    spec_ngram_max: int = 3
    enable_prefix_caching: bool = True
    disable_radix_cache: bool = False
    schedule_policy: str = "fcfs"
    skip_tokenizer_init: bool = False
    log_level: str = "info"
    enable_metrics: bool = False
    decode_log_interval: int = 40
    # Server-side idempotency table (launcher/decode_server.py): /generate
    # requests carrying an `xid` delivery id are deduplicated — a retry of
    # an in-flight submission awaits the SAME engine future and a replay of
    # a completed one returns the cached response, so client retry + router
    # failover-requeue can never double-generate a rollout. Entries are
    # bounded (LRU) and completed entries expire after the TTL.
    idempotency_entries: int = 4096
    idempotency_ttl_s: float = 600.0
    # Crash-mid-stage recovery: weight staging whose last frame arrived
    # more than this many seconds ago is REAPED (dropped with the push-id
    # epoch cleared) the next time any weight endpoint runs — a learner
    # that died mid-push must not leave multi-GiB staging resident until
    # an operator notices. The client additionally aborts its own
    # incomplete push on reconnect (remote_inf_engine.stage_weights).
    # 0 disables the reaper.
    weight_staging_ttl_s: float = 600.0
    # -- fleet KV fabric (core/kv_fabric.py; ISSUE 17) -------------------
    # Content-addressed prefix blocks: every complete pool block gets a
    # chained blake2b key of (token block, parent key, weight_version,
    # kv_dtype). Enables (1) intra-replica dedup — `_admit` forks from
    # ANY resident block run with matching content, regardless of which
    # rid produced it; (2) block-level host-tier lookups beside the
    # rid-exact resume path; (3) peer fetch — on a router hint, the
    # server pulls a sibling's matching block run over the /kv_recv +
    # /kv_commit migration wire instead of re-prefilling. Deduped and
    # fetched streams are bit-identical to the re-prefill oracle (same
    # tokens + same weights => same KV bytes; sampling keys are
    # per-request, not per-block). False restores pre-fabric behavior.
    kv_fabric: bool = True
    # cap on content keys published in the /metrics digest (newest-chain
    # first); bounds the health-poll payload, not the index itself
    kv_fabric_digest_max: int = 512
    # minimum matched COMPLETE blocks before a fabric dedup/fetch fires
    # (tiny matches aren't worth a fork + suffix dispatch)
    kv_fabric_min_blocks: int = 1
    # deadline for one peer block fetch (the /kv_fetch round-trip incl.
    # the pushed frames); on expiry the request degrades to local prefill
    kv_fabric_fetch_timeout_s: float = 30.0


@dataclass
class FaultInjectionConfig:
    """Deterministic fault injection (core/fault_injection.py).

    When enabled, a seed-driven plan perturbs the named seams at every
    cross-component boundary (client HTTP send/recv, router poll/forward,
    server handling, weight stage/commit, host-KV swap, rollout task
    execution) so chaos benches/tests can replay a fleet trace under a
    reproducible fault schedule. `plan` is a JSON list of fault points:

        [{"site": "client.http.recv", "mode": "error_after_effect",
          "at": [3], "match": {"endpoint": "/generate"}}, ...]

    with modes abort / error_after_effect / delay / torn (see
    core/fault_injection.py for the full point schema). Disabled (the
    default), every seam is a single None-check — production pays nothing.
    """

    enabled: bool = False
    seed: int = 0
    plan: str = ""


@dataclass
class RouterConfig:
    """Fleet router (launcher/router.py) policy knobs.

    The router turns N decode-server replicas into one service: policy
    scheduling with prefix affinity, pressure-aware admission with a
    bounded queue, and exactly-once failover (parity:
    realhf/system/gserver_manager.py, grown per ROADMAP item 3).
    """

    # "prefix_affinity" (default: bucketed prompt-prefix hashing with a
    # load override), "least_token_usage", "least_requests", "round_robin"
    schedule_policy: str = "prefix_affinity"
    max_concurrent_rollouts: int = 1024
    max_head_offpolicyness: int = 1_000_000_000
    train_batch_size: int = 1
    health_poll_interval: float = 5.0
    # -- prefix affinity ------------------------------------------------
    # prompt prefixes are hashed at block granularity: the first
    # prefix_block_tokens, 2x, ... up to prefix_max_blocks blocks; the
    # LONGEST hash with a live affinity entry wins (a cheap radix-tree
    # approximation), so GRPO group members / multi-turn sessions /
    # dup-prompt forks land on the replica already holding their donor KV
    prefix_block_tokens: int = 64
    prefix_max_blocks: int = 4
    # affinity-vs-load override: the affine server is skipped when its
    # token load exceeds factor x the least-loaded admissible server's
    # (plus one block of slack) — affinity must not melt a hot replica
    affinity_load_factor: float = 1.5
    # -- pressure-aware admission --------------------------------------
    # fraction of a replica's kv pool the router may fill before the
    # replica stops being admissible (fragmented blocks are subtracted);
    # replicas whose host KV tier is enabled admit to the full pool
    # (eviction offloads instead of dropping)
    kv_pressure_high: float = 0.9
    # cap on running+queued requests per replica (0 = unlimited)
    max_inflight_per_server: int = 0
    # -- bounded queueing ----------------------------------------------
    # requests that no replica can admit wait in a bounded FIFO; past the
    # bound (or past the deadline) they are shed with 429 + Retry-After
    queue_max: int = 1024
    queue_timeout_s: float = 30.0
    retry_after_s: float = 1.0
    # -- failover -------------------------------------------------------
    # consecutive failed health polls before a replica is declared dead:
    # its in-flight qids are requeued onto survivors and its affinity
    # entries drained
    dead_after_failures: int = 2
    # -- per-replica circuit breaker ------------------------------------
    # A replica that is SLOW or erroring (but not yet dead) must be
    # probed, not hammered: after `breaker_trip_after` consecutive bad
    # polls (health/metrics failure, or health RTT above
    # `breaker_slow_s` when > 0) the breaker OPENS and the replica
    # leaves rotation. Once polls look healthy again it goes HALF-OPEN:
    # at most `breaker_probe_requests` in-flight requests are routed
    # there as probes; a completed probe closes the breaker and full
    # traffic (and the replica's surviving affinity entries) return. A
    # transient trip never drains prefix/qid affinity state — only
    # `dead_after_failures` failover does.
    breaker_enabled: bool = True
    breaker_trip_after: int = 3
    breaker_slow_s: float = 0.0
    breaker_probe_requests: int = 1
    # A half-open probe slot is freed by the probe request COMPLETING
    # (_release_qid); a probe whose client died first (deadline shed,
    # crashed caller) would otherwise hold the slot forever and wedge the
    # breaker half-open. Probe charges older than this TTL are expired by
    # the poll loop. 0 disables expiry.
    breaker_probe_ttl_s: float = 60.0
    # -- state expiry ---------------------------------------------------
    # TTL for qid/prefix affinity entries (a crashed client must not leak
    # load accounting forever); 0 disables TTL expiry. route_max_entries
    # LRU-bounds the qid and prefix maps independently of the TTL.
    route_ttl_s: float = 600.0
    route_max_entries: int = 65536
    # -- fleet KV fabric ------------------------------------------------
    # Aggregate the replicas' content-key digests (published through the
    # existing /metrics poll) into a fleet block index: scheduling prices
    # remote-fetch vs local-prefill in the marginal-cost model and ships
    # a {peer, keys} hint so the chosen server fetches the matching block
    # run from the sibling instead of re-prefilling. False restores
    # pre-fabric scheduling (and stops shipping hints).
    kv_fabric: bool = True
    # relative cost of fetching one remote-held prefix token vs
    # prefilling it locally (0 = fetch is free, 1 = no better than
    # prefill); scales the marginal-cost discount for sibling-held blocks
    kv_fabric_fetch_cost_factor: float = 0.25


@dataclass
class SupervisorConfig:
    """Self-healing fleet supervisor (launcher/supervisor.py) policy knobs.

    The supervisor closes ROADMAP item 1's control loop: it polls the
    router's /metrics and each replica's /health, freezes a
    FleetSnapshot, and runs the pure planner `plan_actions(snapshot,
    policy)` whose output drives four safe transitions — scale up (spawn
    through the launcher seam with jittered-backoff retry and crash-loop
    escalation), scale down (/drain to survivors, kill only after the
    drain commits), replace (dead / breaker-open replica drained if
    reachable, killed, respawned), and re-role (prefill<->decode flip via
    drain as the workload mix shifts). Every knob below is a planner
    input, so policy behaviour is unit-testable without a fleet.
    """

    enabled: bool = False
    # control-loop cadence; each tick polls, snapshots, plans, dispatches
    tick_interval_s: float = 1.0
    # -- capacity bounds -------------------------------------------------
    # hard floor no plan may violate (scale-down is refused at the floor;
    # replace preserves capacity and is always allowed)
    min_replicas: int = 1
    max_replicas: int = 8
    # -- SLO signals + hysteresis ---------------------------------------
    # in-flight requests per replica treated as 1.0 utilization; fleet
    # util = (running + router queue depth) / (alive * this)
    util_inflight_target: int = 8
    # hysteresis band: scale up at/above the high mark, down at/below the
    # low mark, and HOLD in between (no flapping)
    scale_up_util: float = 0.85
    scale_down_util: float = 0.30
    # router admission-queue depth that forces a scale-up regardless of
    # the util estimate (queueing is the SLO breach, not a proxy for one)
    scale_up_queue_depth: int = 4
    # -- per-action cooldowns -------------------------------------------
    scale_up_cooldown_s: float = 2.0
    scale_down_cooldown_s: float = 20.0
    replace_cooldown_s: float = 2.0
    rerole_cooldown_s: float = 30.0
    # -- spawn retry / crash-loop escalation ----------------------------
    # consecutive spawn failures on one slot before the supervisor stops
    # retrying it, records a crash_loops_total alert, and continues with
    # the degraded fleet
    spawn_max_attempts: int = 3
    spawn_backoff_s: float = 0.5
    spawn_backoff_max_s: float = 10.0
    # each backoff is scaled by uniform[1-j, 1+j] so simultaneous slot
    # retries don't hammer the launcher in lockstep
    spawn_backoff_jitter: float = 0.25
    # -- drain-as-safe-transition ---------------------------------------
    # a /drain that has not committed within this deadline is aborted and
    # its action rolled back (the victim keeps serving; drain_rollbacks
    # counts the abort) — a hung drain must never wedge the control loop
    drain_deadline_s: float = 30.0
    # -- liveness --------------------------------------------------------
    # consecutive failed /health polls before a replica counts as dead in
    # the snapshot (replace candidate)
    health_fail_threshold: int = 2
    health_timeout_s: float = 5.0
    # -- re-role ---------------------------------------------------------
    rerole_enabled: bool = True
    # |observed prefill work share - provisioned prefill replica share|
    # must exceed this band before a flip is planned (mix-shift hysteresis)
    rerole_band: float = 0.25
    # -- fleet KV fabric ------------------------------------------------
    # Cheap drain: before draining a victim, aggregate the survivors'
    # content-key digests (router pressure snapshots) and pass them as
    # `refetchable`; sessions whose blocks the fleet already holds export
    # META-ONLY (identity + sampling key, no KV bytes — siblings re-fetch
    # or the resume re-prefills). Warm start: a freshly spawned replica
    # is told to pre-fetch the fleet's hottest block runs (/warm_start)
    # before it takes traffic. False disables both fabric integrations.
    kv_fabric: bool = True
    # max sessions a cold replica pulls per surviving peer at warm start
    warm_start_sessions: int = 4


@dataclass
class InferenceEngineConfig:
    """Rollout-side engine config (reference cli_args.py:785)."""

    experiment_name: str | None = None
    trial_name: str | None = None
    max_concurrent_rollouts: None | int = None
    queue_size: None | int = None
    consumer_batch_size: int = 1
    max_head_offpolicyness: int = 0
    enable_rollout_tracing: bool = False
    check_trajectory_format: bool = False
    schedule_policy: str = "round_robin"
    setup_timeout: float = 120.0
    # Per-request deadline: every generation request owns a budget of
    # `request_timeout` seconds from submission, and the REMAINING budget
    # propagates through every stage — router schedule retries, the
    # router's bounded queue wait (shipped as `deadline_s` so the router
    # sheds instead of holding a dead request), 429 Retry-After sleeps,
    # and each failover attempt's transport timeout — so a request never
    # retries past its own deadline.
    request_timeout: float = 3600.0
    request_retries: int = 3
    # Backoff jitter fraction for retry/429 sleeps: each wait is scaled
    # by uniform[1-j, 1+j] so synchronized clients (a whole fleet shed in
    # one poll round) don't retry in lockstep and re-dogpile the server.
    retry_jitter: float = 0.25
    pause_grace_period: float = 0.0
    # Overlapped weight sync: stream staged weight buckets with generation
    # LIVE and pause only around /commit_weights, so the observed generation
    # pause is O(device apply) instead of O(network transfer). Disable to
    # restore the legacy pause-for-the-whole-push behavior.
    weight_sync_overlap: bool = True
    # How many packed weight buckets may be in flight at once during the
    # staged push (device→host gather of bucket N+1 overlaps the HTTP POST
    # of bucket N; bounded so host memory stays at inflight × chunk_mb).
    weight_sync_inflight_buckets: int = 2
    # Router-aware failover: when a /generate attempt exhausts its
    # transport retries (replica died mid-request), the client re-schedules
    # via the fleet router (or the local least-load fallback, excluding the
    # failed address) and re-sends with the SAME delivery id (xid) — the
    # server-side idempotency table makes the retry exactly-once. This caps
    # how many distinct replicas one submission may fail over across.
    fleet_failover_retries: int = 2
    # per-attempt timeout for /schedule_request against the fleet router
    # (queued requests are held by the router up to its queue_timeout_s,
    # so this must comfortably exceed it)
    router_request_timeout: float = 60.0
    # Fleet router policy knobs (launcher/router.py); launchers pass these
    # through when they spawn the router job.
    router: RouterConfig = field(default_factory=RouterConfig)
    # Deterministic fault injection (chaos testing; off by default).
    fault_injection: FaultInjectionConfig = field(
        default_factory=FaultInjectionConfig
    )


@dataclass
class _Timer:
    experiment_name: str = ""
    trial_name: str = ""
    fileroot: str = ""
    freq_epochs: int | None = None
    freq_steps: int | None = None
    freq_secs: int | None = None


@dataclass
class EvaluatorConfig(_Timer):
    pass


@dataclass
class SaverConfig(_Timer):
    pass


@dataclass
class RecoverConfig(_Timer):
    """Step-level crash recovery (utils/recover.py).

    Each dump is written to a fresh `step-{G}.tmp` directory, sealed with a
    checksummed, fsynced MANIFEST.json, atomically renamed to `step-{G}`,
    and only then pruned to `keep_last` — dying at any instant leaves every
    previously committed recovery point intact. `load` walks committed
    steps newest→oldest and skips torn/manifest-mismatched candidates
    instead of crashing.
    """

    mode: str = "disabled"  # "disabled" | "auto" | "fault" | "resume"
    retries: int = 3
    # committed step-{G} recovery points retained after each successful
    # dump (newest keep_last survive pruning); >= 1. Two is the floor that
    # makes a torn newest checkpoint recoverable from its predecessor.
    keep_last: int = 2


@dataclass
class WandBConfig:
    mode: str = "disabled"
    wandb_base_url: str = ""
    wandb_api_key: str = ""
    entity: str | None = None
    project: str | None = None
    name: str | None = None
    job_type: str | None = None
    group: str | None = None
    notes: str | None = None
    tags: list[str] | None = None
    config: dict | None = None
    id_suffix: str | None = "train"


@dataclass
class SwanlabConfig:
    project: str | None = None
    name: str | None = None
    config: dict | None = None
    logdir: str | None = None
    mode: str | None = "disabled"
    api_key: str | None = None


@dataclass
class TensorBoardConfig:
    path: str | None = None


@dataclass
class StatsLoggerConfig:
    experiment_name: str = ""
    trial_name: str = ""
    fileroot: str = ""
    wandb: WandBConfig = field(default_factory=WandBConfig)
    swanlab: SwanlabConfig = field(default_factory=SwanlabConfig)
    tensorboard: TensorBoardConfig = field(default_factory=TensorBoardConfig)


@dataclass
class ClusterSpecConfig:
    name_resolve: NameResolveConfig = field(default_factory=NameResolveConfig)
    cluster_name: str = "local"
    fileroot: str = "/tmp/areal_tpu"
    n_nodes: int = 1
    n_accelerators_per_node: int = 8  # chips per host (v5p host = 4, v5e = 8)


@dataclass
class DatasetConfig:
    path: str = ""
    type: str = ""
    batch_size: int = 1
    shuffle: bool = True
    pin_memory: bool = False
    num_workers: int = 0
    drop_last: bool = True
    max_length: int | None = None


@dataclass
class SlurmLauncherConfig:
    srun_additional_args: str = ""
    additional_bash_cmds: list[str] | None = None
    container_type: str = "none"
    mount: str = ""
    trainer_image: str | None = None
    inference_server_image: str | None = None


@dataclass
class LauncherConfig:
    inference_server_cpus_per_accelerator: int = 4
    inference_server_mem_per_accelerator: int = 32 * 1024
    trainer_cpus_per_accelerator: int = 4
    trainer_mem_per_accelerator: int = 32 * 1024
    inference_server_env_vars: str = ""
    trainer_env_vars: str = ""
    # Disaggregated role fleet: of the gen data-parallel replicas, launch
    # this many with --role prefill (compute-bound: prompt prefills only,
    # KV streamed to the decode replicas) and the REST with --role decode.
    # 0 (default) launches every replica unified. Must leave at least one
    # decode replica (prefill_replicas < gen dp size).
    prefill_replicas: int = 0
    # Self-healing fleet supervisor (launcher/supervisor.py): SLO
    # autoscaling + replace/re-role over the decode fleet. Off by default;
    # when enabled the launcher runs the control loop next to the router.
    supervisor: SupervisorConfig = field(default_factory=SupervisorConfig)
    slurm: SlurmLauncherConfig = field(default_factory=SlurmLauncherConfig)


@dataclass
class BaseExperimentConfig:
    """Root experiment config (reference cli_args.py:1145)."""

    experiment_name: str = "experiment"
    trial_name: str = "trial"
    cluster: ClusterSpecConfig = field(default_factory=ClusterSpecConfig)
    allocation_mode: str = ""
    seed: int = 1
    total_train_epochs: int = 1
    total_train_steps: int | None = None
    total_train_n_seqs: int | None = None
    tokenizer_path: str = ""
    train_dataset: DatasetConfig = field(default_factory=DatasetConfig)
    valid_dataset: DatasetConfig | None = None
    saver: SaverConfig = field(default_factory=SaverConfig)
    evaluator: EvaluatorConfig = field(default_factory=EvaluatorConfig)
    stats_logger: StatsLoggerConfig = field(default_factory=StatsLoggerConfig)
    recover: RecoverConfig = field(default_factory=RecoverConfig)
    decode: JaxDecodeConfig = field(default_factory=JaxDecodeConfig)
    launcher: LauncherConfig = field(default_factory=LauncherConfig)


@dataclass
class SFTConfig(BaseExperimentConfig):
    model: TrainEngineConfig = field(default_factory=TrainEngineConfig)


@dataclass
class RWConfig(BaseExperimentConfig):
    model: TrainEngineConfig = field(default_factory=TrainEngineConfig)


@dataclass
class GRPOConfig(BaseExperimentConfig):
    async_training: bool = True
    gconfig: GenerationHyperparameters = field(
        default_factory=GenerationHyperparameters
    )
    rollout: InferenceEngineConfig = field(default_factory=InferenceEngineConfig)
    actor: PPOActorConfig = field(default_factory=PPOActorConfig)
    ref: PPOActorConfig = field(default_factory=PPOActorConfig)
    # Which rollout workflow drives episodes: single-shot verifiable reward,
    # the self-correction loop (ref: examples/multi-turn-math/train.py), or
    # the VLM variant (ref: examples/vlm/clevr_count_70k_grpo.py).
    workflow: str = "rlvr"  # "rlvr" | "multi_turn" | "vision_rlvr" | "tir"
    # multi_turn knobs (ref: areal/workflow/multi_turn.py)
    max_turns: int = 3
    turn_discount: float = 0.9
    # tir knobs (ref: examples/tir/tir_workflow.py)
    max_tool_calls: int = 4
    tool_timeout_seconds: float = 8.0


@dataclass
class PPOConfig(GRPOConfig):
    critic: PPOCriticConfig = field(default_factory=PPOCriticConfig)


# ---------------------------------------------------------------------------
# CLI / YAML loading (reference cli_args.py:1247-1314)
# ---------------------------------------------------------------------------


def parse_cli_args(argv: list[str]):
    """Parse ``--config file.yaml key=value ...`` into (dict, overrides)."""
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", type=str, default=None, help="YAML config file")
    args, overrides = parser.parse_known_args(argv)
    cfg_dict = {}
    if args.config is not None:
        with open(args.config) as f:
            cfg_dict = yaml.safe_load(f) or {}
    kv = []
    for item in overrides:
        if "=" not in item:
            raise ValueError(f"override {item!r} must be of the form key=value")
        k, v = item.split("=", 1)
        kv.append((k, v))
    return cfg_dict, kv


def load_expr_config(argv: list[str], config_cls, ignore_unknown: bool = False):
    """Load a structured experiment config from CLI argv.

    Returns (config, config_file_dict) like the reference's
    `load_expr_config` (cli_args.py:1280). `ignore_unknown` lets a
    subset-view consumer (the launcher) parse a subclass's YAML.
    """
    cfg_dict, overrides = parse_cli_args(argv)
    config = structured.from_dict(
        config_cls, cfg_dict, ignore_unknown=ignore_unknown
    )
    for k, v in overrides:
        try:
            structured.apply_override(config, k, v)
        except structured.UnknownFieldError:
            # subset view: subclass-only fields are fine to skip; bad
            # VALUES for known fields still raise below
            if not ignore_unknown:
                raise
    # propagate experiment/trial names into nested configs that need them
    for attr in ("saver", "evaluator", "stats_logger", "recover"):
        sub = getattr(config, attr, None)
        if sub is not None:
            if not sub.experiment_name:
                sub.experiment_name = config.experiment_name
            if not sub.trial_name:
                sub.trial_name = config.trial_name
            if hasattr(sub, "fileroot") and not sub.fileroot:
                sub.fileroot = config.cluster.fileroot
    for attr in ("rollout",):
        sub = getattr(config, attr, None)
        if sub is not None:
            if sub.experiment_name is None:
                sub.experiment_name = config.experiment_name
            if sub.trial_name is None:
                sub.trial_name = config.trial_name
    for attr in ("actor", "ref", "critic", "model"):
        sub = getattr(config, attr, None)
        if sub is not None:
            if not sub.experiment_name:
                sub.experiment_name = config.experiment_name
            if not sub.trial_name:
                sub.trial_name = config.trial_name
    return config, cfg_dict


def save_config(config, save_dir: str) -> str:
    """Persist the resolved config as YAML in the run directory."""
    os.makedirs(save_dir, exist_ok=True)
    path = os.path.join(save_dir, "config.yaml")
    with open(path, "w") as f:
        yaml.safe_dump(structured.to_dict(config), f, sort_keys=False)
    return path


def get_user() -> str:
    try:
        return getpass.getuser()
    except Exception:
        return "unknown"
