"""Rollout workflow contract (parity: areal/api/workflow_api.py:11).

A workflow is one agentic episode: given an inference engine and one dataset
item, produce a training trajectory (padded dict-of-arrays with batch dim =
number of samples, e.g. a GRPO group) or None to reject the episode.
"""

from __future__ import annotations

import abc
from typing import TYPE_CHECKING, Any

if TYPE_CHECKING:
    from areal_tpu.api.engine_api import InferenceEngine


class RolloutWorkflow(abc.ABC):
    @abc.abstractmethod
    async def arun_episode(
        self, engine: "InferenceEngine", data: dict[str, Any]
    ) -> dict[str, Any] | None:
        """Run one episode; return a padded trajectory batch or None.

        Returning None marks the episode rejected (filtered out); the
        executor decrements running without incrementing accepted.
        """
        raise NotImplementedError()


def encode_prompt(
    tokenizer, data: dict, enable_thinking: bool | None = None
) -> list:
    """Shared prompt encoding for workflows: pre-tokenized input_ids win,
    else chat-template messages, else raw prompt text. `enable_thinking`
    is forwarded to the chat template whenever set (False matters: Qwen3
    templates default thinking ON); None omits the kwarg entirely."""
    import numpy as np

    if "input_ids" in data:
        return list(np.asarray(data["input_ids"]).reshape(-1))
    assert tokenizer is not None, "need a tokenizer to encode messages/prompt"
    if "messages" in data:
        kw = dict(add_generation_prompt=True, tokenize=True)
        if enable_thinking is not None:
            kw["enable_thinking"] = enable_thinking
        return tokenizer.apply_chat_template(data["messages"], **kw)
    return tokenizer.encode(data["prompt"])
