"""Agent / environment APIs (parity: realhf/api/core/agent_api.py:15 Agent,
realhf/impl/environment EnvironmentService).

The legacy reference runs agents inside RolloutWorkers that talk to
generation servers through obs/act queues and a PartialRolloutManager. In
the TPU stack the equivalent machinery is the async workflow executor, so
the agent contract is expressed directly against `InferenceEngine` and the
adapter `AgentWorkflow` plugs any Agent+env pair into the standard rollout
pipeline (submit/wait/prepare_batch, staleness control, interrupt-resume —
all inherited for free).
"""

from __future__ import annotations

import abc
from typing import Any


class EnvironmentService(abc.ABC):
    """Gym-style async environment (parity: realhf EnvironmentService)."""

    @abc.abstractmethod
    async def reset(self, seed: int | None = None, options: dict | None = None):
        """-> observation"""

    @abc.abstractmethod
    async def step(self, action: Any):
        """-> (observation, reward, terminated, truncated, info)"""

    async def close(self) -> None:
        pass


class Agent(abc.ABC):
    """Collects one trajectory for one prompt (parity: agent_api.py:15
    `collect_trajectory`; obs/act queues are subsumed by direct async calls)."""

    @abc.abstractmethod
    async def collect_trajectory(
        self,
        engine: Any,  # InferenceEngine
        prompt: dict[str, Any],
        env: EnvironmentService,
    ) -> list[dict[str, Any]]:
        """-> list of training rows (input_ids/loss_mask/logprobs/versions/
        rewards per row), possibly empty to reject the episode."""
