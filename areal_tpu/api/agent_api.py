"""Agent / environment APIs (parity: realhf/api/core/agent_api.py:15 Agent,
realhf/impl/environment EnvironmentService).

The legacy reference runs agents inside RolloutWorkers that talk to
generation servers through obs/act queues and a PartialRolloutManager. In
the TPU stack the equivalent machinery is the async workflow executor, so
the agent contract is expressed directly against `InferenceEngine` and the
adapter `AgentWorkflow` plugs any Agent+env pair into the standard rollout
pipeline (submit/wait/prepare_batch, staleness control, interrupt-resume —
all inherited for free).
"""

from __future__ import annotations

import abc
from typing import Any


class EnvironmentService(abc.ABC):
    """Gym-style async environment (parity: realhf EnvironmentService)."""

    @abc.abstractmethod
    async def reset(self, seed: int | None = None, options: dict | None = None):
        """-> observation"""

    @abc.abstractmethod
    async def step(self, action: Any):
        """-> (observation, reward, terminated, truncated, info)"""

    async def close(self) -> None:
        pass


class Agent(abc.ABC):
    """Collects one trajectory for one prompt (parity: agent_api.py:15
    `collect_trajectory`; obs/act queues are subsumed by direct async calls)."""

    @abc.abstractmethod
    async def collect_trajectory(
        self,
        engine: Any,  # InferenceEngine
        prompt: dict[str, Any],
        env: EnvironmentService,
    ) -> list[dict[str, Any]]:
        """-> list of training rows (input_ids/loss_mask/logprobs/versions/
        rewards per row), possibly empty to reject the episode."""


# ---------------------------------------------------------------------------
# Env registry (parity: realhf/api/core/env_api.py register_environment /
# make_env) — configs name an env by string; implementations self-register
# at import time.
# ---------------------------------------------------------------------------

ALL_ENV_CLASSES: dict[str, type] = {}


def register_environment(name: str, env_cls: type) -> None:
    assert name not in ALL_ENV_CLASSES, f"env {name!r} already registered"
    assert "/" not in name
    ALL_ENV_CLASSES[name] = env_cls


def make_env(name: str, **kwargs) -> EnvironmentService:
    """Instantiate a registered environment by name. Built-in envs
    (agent/ modules) self-register on import; imported lazily here so
    config-driven callers need no import side effects."""
    import importlib

    for mod in ("areal_tpu.agent.math_single_step",
                "areal_tpu.agent.math_code_env"):
        importlib.import_module(mod)
    return ALL_ENV_CLASSES[name](**kwargs)


class NullEnvironment(EnvironmentService):
    """No-op env (parity: env_api.py NullEnvironment) for pure-generation
    agents: step() terminates immediately with zero reward."""

    async def reset(self, seed: int | None = None, options: dict | None = None):
        return None

    async def step(self, action: Any):
        return None, 0.0, True, False, {}


register_environment("null", NullEnvironment)
