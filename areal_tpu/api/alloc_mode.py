"""Allocation-mode expressions: how devices are split between training and
generation, and how each side is parallelised.

Parity target: areal/api/alloc_mode.py:34 (ParallelStrategy), :241
(AllocationMode), :312 (grammar). We keep the same expression syntax so
reference configs port unchanged, and add the TPU-native backend name
``jax`` (in-process GSPMD engine for both decode and training) alongside
the reference names (``sglang``/``vllm`` for inference, ``fsdp``/``megatron``
for training — accepted and mapped onto the jax engine's mesh dims).

Examples::

    d4t2p1                      # colocated / training-only (SFT)
    jax:d4t2+jax:d8             # decoupled: 8-chip decode + 8-chip trainer
    sglang:d4t2+fsdp:d8         # reference syntax, accepted verbatim
    jax:d2t4|jax:d2t4           # colocated RL (train & gen share chips)
    jax:d4t2+eval               # LLM server + CPU eval workers
    (attn:d2t2|ffn:d2e2)        # MoE hybrid: attention vs expert sharding

Semantics are positional: in ``A+B`` and ``A|B``, the left side is always
the inference deployment and the right side the trainer. A standalone
``<backend>:<dims>`` expression is an inference-only deployment when the
backend serves inference (jax/jetstream/sglang/vllm) and a training-only
deployment when it is train-specific (fsdp/megatron); a standalone
training-only allocation is normally written as bare dims (``d4t2p1``).
Because ``jax`` serves both roles, ``jax:<dims>`` standalone is ALWAYS
inference-only — write bare dims for a jax trainer.

On TPU the 5-D strategy maps onto a single `jax.sharding.Mesh` with named
axes; see areal_tpu/parallel/mesh.py.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from lark import Lark, Transformer


class AllocationType(enum.Enum):
    COLOCATE = 0
    DECOUPLED_TRAIN = 1
    LLM_SERVER_ONLY = 2
    DECOUPLED_EVAL = 3


class AllocationValidationError(Exception):
    pass


class InvalidAllocationModeError(Exception):
    pass


@dataclass
class ParallelStrategy:
    """5-D parallel strategy (TP, PP, DP, CP, EP + expert-TP).

    Mirrors reference areal/api/alloc_mode.py:34. On TPU these become mesh
    axis sizes rather than process-group sizes:

    - tensor_parallel_size   → mesh axis "tp" (MXU-sharded matmuls)
    - pipeline_parallel_size → mesh axis "pp" (layer-sharded stages)
    - data_parallel_size     → mesh axis "dp"/"fsdp" (batch + param shards)
    - context_parallel_size  → mesh axis "sp" (sequence sharding / ring attn)
    - expert_parallel_size   → mesh axis "ep" (MoE expert sharding)
    """

    tensor_parallel_size: int = 1
    pipeline_parallel_size: int = 1
    data_parallel_size: int = 1
    context_parallel_size: int = 1
    expert_parallel_size: int = 1
    expert_tensor_parallel_size: int = 1

    def __post_init__(self):
        if self.expert_parallel_size > 1:
            emp = (
                self.pipeline_parallel_size
                * self.expert_tensor_parallel_size
                * self.expert_parallel_size
            )
            if self.world_size % emp != 0:
                raise AllocationValidationError(
                    f"Expert model parallel size {emp} does not divide "
                    f"world size {self.world_size}"
                )

    # -- sizes ----------------------------------------------------------
    @property
    def world_size(self) -> int:
        return (
            self.tensor_parallel_size
            * self.pipeline_parallel_size
            * self.data_parallel_size
            * self.context_parallel_size
        )

    @property
    def expert_model_parallel_size(self) -> int:
        return (
            self.pipeline_parallel_size
            * self.expert_tensor_parallel_size
            * self.expert_parallel_size
        )

    @property
    def expert_data_parallel_size(self) -> int:
        if self.expert_parallel_size <= 1:
            return self.data_parallel_size
        return self.world_size // self.expert_model_parallel_size

    # -- abbreviations --------------------------------------------------
    @property
    def tp_size(self) -> int:
        return self.tensor_parallel_size

    @property
    def pp_size(self) -> int:
        return self.pipeline_parallel_size

    @property
    def dp_size(self) -> int:
        return self.data_parallel_size

    @property
    def cp_size(self) -> int:
        return self.context_parallel_size

    @property
    def ep_size(self) -> int:
        return self.expert_parallel_size

    @property
    def etp_size(self) -> int:
        return self.expert_tensor_parallel_size

    def __str__(self):
        dims = []
        for tag, size in (
            ("d", self.data_parallel_size),
            ("t", self.tensor_parallel_size),
            ("p", self.pipeline_parallel_size),
            ("c", self.context_parallel_size),
            ("e", self.expert_parallel_size),
        ):
            if size != 1 or tag == "d":
                dims.append(f"{tag}{size}")
        return "".join(dims)


INFERENCE_BACKENDS = ("jax", "jetstream", "sglang", "vllm")
TRAIN_BACKENDS = ("jax", "fsdp", "megatron")
# Dims an inference deployment may specify (no context/expert parallel: the
# decode engine derives those internally).
_INF_DIMS = ("d", "t", "p")

# One backend token set; role is decided by position (left of +/| = inference,
# right = train) which keeps the grammar unambiguous even though "jax" can
# serve either role.
ALLOCATION_GRAMMAR = r"""
    start: expression

    expression: disaggregate_expr | colocate_expr | eval_expr | backend_para | plain_train

    disaggregate_expr: backend_para "+" rhs_para
    colocate_expr: backend_para "|" rhs_para
    eval_expr: backend_para "+" EVAL

    rhs_para: backend_para | plain_train
    backend_para: BACKEND ":" common_dim+
        | BACKEND ":" hybrid_moe
    plain_train: common_dim+
        | hybrid_moe

    hybrid_moe: "(" attn_section "|" ffn_section ")"
        | attn_section "|" ffn_section
    attn_section: "attn" ":" attn_dim+
    ffn_section: "ffn" ":" ffn_dim+

    common_dim: DIM_TYPE NUMBER
    attn_dim: ATTN_DIM_TYPE NUMBER
    ffn_dim: FFN_DIM_TYPE NUMBER

    DIM_TYPE: "p" | "d" | "t" | "c" | "e"
    ATTN_DIM_TYPE: "c" | "d" | "t" | "p"
    FFN_DIM_TYPE: "d" | "e" | "t" | "p"

    EVAL: "cpu" | "eval"
    BACKEND: "jetstream" | "sglang" | "vllm" | "megatron" | "fsdp" | "jax"
    NUMBER: /[1-9][0-9]*/

    %import common.WS
    %ignore WS
"""

_DIM_FIELD = {
    "d": "data_parallel_size",
    "t": "tensor_parallel_size",
    "p": "pipeline_parallel_size",
    "c": "context_parallel_size",
    "e": "expert_parallel_size",
}


def _strategy_from_dims(dims: list[tuple[str, int]], what: str) -> ParallelStrategy:
    kwargs: dict[str, int] = {}
    for tag, size in dims:
        fieldname = _DIM_FIELD[tag]
        if fieldname in kwargs:
            raise AllocationValidationError(
                f"duplicate dimension '{tag}' in {what} strategy"
            )
        kwargs[fieldname] = size
    return ParallelStrategy(**kwargs)


class _AllocTransformer(Transformer):
    def NUMBER(self, tok):
        return int(tok)

    def common_dim(self, items):
        return (str(items[0]), items[1])

    attn_dim = common_dim
    ffn_dim = common_dim

    def backend_para(self, items):
        backend = str(items[0])
        rest = items[1:]
        if len(rest) == 1 and isinstance(rest[0], tuple) and rest[0][0] == "moe":
            return ("para", backend, rest[0][1], ())
        dims = list(rest)
        return ("para", backend, _strategy_from_dims(dims, backend), tuple(t for t, _ in dims))

    def plain_train(self, items):
        if len(items) == 1 and isinstance(items[0], tuple) and items[0][0] == "moe":
            return ("para", None, items[0][1], ())
        dims = list(items)
        return ("para", None, _strategy_from_dims(dims, "train"), tuple(t for t, _ in dims))

    def rhs_para(self, items):
        return items[0]

    def attn_section(self, items):
        return ("attn", list(items))

    def ffn_section(self, items):
        return ("ffn", list(items))

    def hybrid_moe(self, items):
        sections = dict(items)
        attn = _strategy_from_dims(sections["attn"], "attention")
        ffn_dims = dict(sections["ffn"])
        # In the hybrid syntax, the ffn section re-expresses the same device
        # grid with expert dims; fold e/etp into the attention strategy.
        strategy = ParallelStrategy(
            tensor_parallel_size=attn.tensor_parallel_size,
            pipeline_parallel_size=attn.pipeline_parallel_size,
            data_parallel_size=attn.data_parallel_size,
            context_parallel_size=attn.context_parallel_size,
            expert_parallel_size=ffn_dims.get("e", 1),
            expert_tensor_parallel_size=ffn_dims.get("t", 1),
        )
        ffn_world = (
            ffn_dims.get("d", 1)
            * ffn_dims.get("e", 1)
            * ffn_dims.get("t", 1)
            * ffn_dims.get("p", 1)
        )
        if ffn_world != strategy.world_size:
            raise AllocationValidationError(
                f"MoE hybrid: ffn world size {ffn_world} != attn world size "
                f"{strategy.world_size}"
            )
        if ffn_dims.get("p", 1) != attn.pipeline_parallel_size:
            raise AllocationValidationError(
                "MoE hybrid: ffn and attn pipeline sizes must match"
            )
        return ("moe", strategy)

    def disaggregate_expr(self, items):
        return ("disagg", items[0], items[1])

    def colocate_expr(self, items):
        return ("colo", items[0], items[1])

    def eval_expr(self, items):
        return ("eval", items[0])

    def expression(self, items):
        return items[0]

    def start(self, items):
        return items[0]


_parser = Lark(ALLOCATION_GRAMMAR, parser="earley")
_transformer = _AllocTransformer()


def _check_inference_para(node, expr: str):
    _, backend, strategy, dim_tags = node
    if backend is None:
        raise AllocationValidationError(
            f"inference side of {expr!r} must name a backend "
            f"(one of {INFERENCE_BACKENDS})"
        )
    if backend not in INFERENCE_BACKENDS:
        raise AllocationValidationError(
            f"{backend!r} is not an inference backend (expected one of "
            f"{INFERENCE_BACKENDS}); in 'A+B' / 'A|B' the left side is the "
            "inference deployment"
        )
    bad = [t for t in dim_tags if t not in _INF_DIMS]
    # Validate on strategy values too so MoE-hybrid syntax (which carries no
    # dim tags) cannot smuggle cp/ep onto the inference side.
    if strategy.context_parallel_size > 1 or strategy.expert_parallel_size > 1:
        bad += [
            t
            for t, sz in (
                ("c", strategy.context_parallel_size),
                ("e", strategy.expert_parallel_size),
            )
            if sz > 1 and t not in bad
        ]
    if bad:
        raise AllocationValidationError(
            f"dimension(s) {bad} are not valid for an inference deployment "
            f"(allowed: {_INF_DIMS}); for a train-only allocation write bare "
            f"dims, e.g. 'd4c2'"
        )
    return backend, strategy


def _check_train_para(node, expr: str):
    _, backend, strategy, _ = node
    if backend is None:
        backend = "jax"
    if backend not in TRAIN_BACKENDS:
        raise AllocationValidationError(
            f"{backend!r} is not a train backend (expected one of "
            f"{TRAIN_BACKENDS}); in 'A+B' / 'A|B' the right side is the trainer"
        )
    return backend, strategy


@dataclass
class AllocationMode:
    """Parsed allocation configuration (parity: areal/api/alloc_mode.py:241)."""

    type_: AllocationType
    gen: ParallelStrategy = field(default_factory=ParallelStrategy)
    train: ParallelStrategy | None = None
    gen_backend: str | None = None
    train_backend: str | None = None

    @property
    def gen_instance_size(self) -> int:
        """Devices per inference instance (tp × pp; dp counts instances)."""
        return self.gen.tp_size * self.gen.pp_size

    @property
    def gen_world_size(self) -> int:
        return self.gen.world_size if self.gen is not None else 0

    @property
    def train_world_size(self) -> int:
        return self.train.world_size if self.train is not None else 0

    @classmethod
    def from_str(cls, allocation_mode: str) -> "AllocationMode":
        if not (allocation_mode or "").strip():
            # Empty mode = colocated single-program default: train strategy
            # is decided by the engine (dp over all local devices), decode
            # runs in-process on the same chips.
            return cls(type_=AllocationType.COLOCATE, train=None)
        try:
            tree = _parser.parse(allocation_mode)
            node = _transformer.transform(tree)
        except AllocationValidationError:
            raise
        except Exception as e:  # lark raises many exception types
            raise InvalidAllocationModeError(
                f"cannot parse allocation mode {allocation_mode!r}: {e}"
            ) from e
        return cls._from_node(node, allocation_mode)

    @classmethod
    def _from_node(cls, node, expr: str) -> "AllocationMode":
        kind = node[0]
        if kind == "para":
            _, backend, strategy, dim_tags = node
            if backend is None or backend not in INFERENCE_BACKENDS:
                # bare dims, or a train-only backend like fsdp/megatron
                backend, strategy = _check_train_para(node, expr)
                return cls(
                    type_=AllocationType.COLOCATE,
                    gen=ParallelStrategy(),
                    train=strategy,
                    train_backend=backend,
                )
            # Standalone backend-qualified expression → inference-only.
            # ("jax" standalone is always inference; see module docstring.)
            backend, strategy = _check_inference_para(node, expr)
            return cls(
                type_=AllocationType.LLM_SERVER_ONLY,
                gen=strategy,
                gen_backend=backend,
            )
        if kind == "eval":
            backend, strategy = _check_inference_para(node[1], expr)
            return cls(
                type_=AllocationType.DECOUPLED_EVAL,
                gen=strategy,
                gen_backend=backend,
            )
        if kind in ("disagg", "colo"):
            gen_backend, gen = _check_inference_para(node[1], expr)
            train_backend, train = _check_train_para(node[2], expr)
            if kind == "colo" and gen.world_size != train.world_size:
                # COLOCATE means gen and train share the same chips; the
                # reference enforces matching world sizes and so do we.
                raise AllocationValidationError(
                    f"colocated allocation {expr!r} requires matching world "
                    f"sizes, got gen={gen.world_size} train={train.world_size}"
                )
            return cls(
                type_=(
                    AllocationType.DECOUPLED_TRAIN
                    if kind == "disagg"
                    else AllocationType.COLOCATE
                ),
                gen=gen,
                gen_backend=gen_backend,
                train=train,
                train_backend=train_backend,
            )
        raise InvalidAllocationModeError(f"unknown node {node!r}")

    def check_hbm(
        self,
        model_cfg,
        device_kind: str,
        *,
        microbatch_tokens: int = 8192,
        remat: bool = True,
        fsdp: bool = True,
        zero1: bool = False,
        pipeline_schedule: str = "1f1b",
        virtual_pp: int = 1,
        decode_slots: int = 64,
        decode_context: int = 32768,
        decode_pool_tokens: int | None = None,
        decode_weight_dtype: str = "fp",
        utilization: float = 0.9,
    ) -> dict:
        """Validate that this allocation's train AND gen halves fit the
        target chip's HBM, using the closed-form estimator (utils/hbm.py).

        The reference validates allocation strings only for arithmetic
        consistency (areal/api/alloc_mode.py world-size checks); chips that
        OOM three hours into a run are discovered the hard way. Here the
        plan is rejected up front. Raises AllocationValidationError with
        the per-component breakdown; returns {"train": ..., "gen": ...}
        breakdowns when both fit.
        """
        from areal_tpu.utils import hbm

        report: dict = {}
        if self.train is not None:
            est = hbm.estimate_train_hbm(
                model_cfg,
                dp=self.train.dp_size,
                tp=self.train.tp_size,
                pp=self.train.pp_size,
                sp=self.train.cp_size,
                microbatch_tokens=microbatch_tokens,
                remat=remat,
                fsdp=fsdp,
                zero1=zero1,
                pipeline_schedule=pipeline_schedule,
                virtual_pp=virtual_pp,
            )
            try:
                hbm.check_fit(est, device_kind, utilization=utilization)
            except MemoryError as e:
                raise AllocationValidationError(f"train half: {e}") from None
            report["train"] = est.breakdown()
        if self.gen is not None and self.gen_world_size > 0:
            est = hbm.estimate_decode_hbm(
                model_cfg,
                tp=self.gen.tp_size,
                slots=decode_slots,
                context_length=decode_context,
                pool_tokens=decode_pool_tokens,
                weight_dtype=decode_weight_dtype,
            )
            try:
                hbm.check_fit(est, device_kind, utilization=utilization)
            except MemoryError as e:
                raise AllocationValidationError(f"gen half: {e}") from None
            report["gen"] = est.breakdown()
        return report
