"""Scheduler API (parity: areal/api/scheduler_api.py:36 Scheduler ABC).

The experimental single-controller mode: a controller process asks a
Scheduler to create worker processes, instantiate engines inside them, and
invoke engine methods remotely. The TPU implementation backs this with the
HTTP RPC pair in areal_tpu/scheduler/rpc/.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Any


@dataclasses.dataclass
class Worker:
    id: str
    ip: str
    ports: list[str] = dataclasses.field(default_factory=list)

    @property
    def rpc_addr(self) -> str:
        return f"{self.ip}:{self.ports[0]}"


@dataclasses.dataclass
class SchedulingSpec:
    """Resources for one worker (reference Scheduling, engine_api.py:24)."""

    cpu: int = 4
    gpu: int = 0  # accelerator chips (TPU here)
    mem: int = 16 * 1024  # MB
    port_count: int = 1
    env_vars: dict[str, str] = dataclasses.field(default_factory=dict)


class Scheduler(abc.ABC):
    @abc.abstractmethod
    def create_workers(
        self, role: str, spec: SchedulingSpec, count: int, **kwargs
    ) -> list[str]:
        """Spawn `count` workers; returns worker ids."""

    @abc.abstractmethod
    def get_workers(self, role: str, timeout: float | None = None) -> list[Worker]:
        """Wait until the role's workers are up; return their endpoints."""

    @abc.abstractmethod
    def delete_workers(self, role: str | None = None) -> None:
        """Tear down workers (all roles when role is None)."""

    @abc.abstractmethod
    def create_engine(
        self, worker_id: str, engine_type: str, *args, **kwargs
    ) -> Any:
        """Instantiate an engine (by import path) inside a worker."""

    @abc.abstractmethod
    def call_engine(self, worker_id: str, method: str, *args, **kwargs) -> Any:
        """Invoke a method on the worker's engine and return the result."""
