"""Engine contracts: TrainEngine and InferenceEngine.

Parity target: areal/api/engine_api.py:41 (TrainEngine), :347
(InferenceEngine). Method names are preserved so reference training scripts
port mechanically. Semantics differ where SPMD-on-TPU differs from
one-process-per-GPU torch:

- The reference runs N trainer processes (torchrun) that each own a model
  shard and coordinate via NCCL process groups. Here ONE controller process
  per host drives a global jit program over a jax.sharding.Mesh; "process
  group" methods therefore describe mesh topology rather than communicator
  handles. Multi-host execution uses jax.distributed with the same code.
- `train_batch`'s contract is unchanged: loss_fn over packed 1-D inputs,
  loss_weight_fn for global normalization across micro-batches
  (engine_api.py:242-274).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any, Callable

from areal_tpu.api.alloc_mode import ParallelStrategy
from areal_tpu.api.io_struct import (
    FinetuneSpec,
    ModelRequest,
    ModelResponse,
    SaveLoadMeta,
    WeightUpdateMeta,
)

if TYPE_CHECKING:
    from areal_tpu.api.workflow_api import RolloutWorkflow


@dataclass
class Scheduling:
    """Resource requirements for scheduling one engine worker
    (parity: areal/api/engine_api.py:24)."""

    cpu: int = 4
    gpu: int = 0
    tpu: int = 1
    mem: int = 32 * 1024  # MB
    port_count: int = 2
    env_vars: dict[str, str] = field(default_factory=dict)


class TrainEngine(abc.ABC):
    """SPMD training engine contract (parity: engine_api.py:41)."""

    # -- lifecycle ------------------------------------------------------
    def create_process_group(
        self, parallel_strategy: ParallelStrategy | None = None
    ) -> None:
        """Initialise the device mesh for `parallel_strategy` (and
        jax.distributed in multi-host deployments)."""
        raise NotImplementedError()

    def initialize(
        self,
        addr: str | None = None,
        ft_spec: FinetuneSpec | None = None,
    ) -> None:
        """Load the model onto the mesh and build the optimizer."""
        raise NotImplementedError()

    def destroy(self) -> None:
        """Release device buffers."""

    # -- topology introspection ----------------------------------------
    @property
    def data_parallel_rank(self) -> int:
        raise NotImplementedError()

    @property
    def data_parallel_world_size(self) -> int:
        raise NotImplementedError()

    @property
    def is_data_parallel_head(self) -> bool:
        raise NotImplementedError()

    def get_scheduling_config(self) -> Scheduling:
        return Scheduling()

    # -- mode -----------------------------------------------------------
    def train(self, mode: bool = True):
        """Toggle train mode (dropout etc.; most TPU configs disable dropout)."""
        return self

    def eval(self):
        return self.train(False)

    # -- weights --------------------------------------------------------
    def update_weights(self, meta: WeightUpdateMeta) -> None:
        """Push current weights to the connected inference engine."""
        raise NotImplementedError()

    def update_weights_async(self, meta: WeightUpdateMeta | None = None):
        """Start a weight push WITHOUT blocking the train loop: the stage
        phase (host gather + bucket streaming, for transports that support
        staging) runs on a background thread while the caller keeps
        training. Returns a handle with `join()` (wait for staging),
        `commit()` (join, then enter the pause window and commit — the
        synchronization point the caller chooses) and `abort()`. Engines
        whose transport has no stage/commit split may run the whole push on
        the background thread and make commit() a bare join."""
        raise NotImplementedError()

    def connect_engine(self, engine: "InferenceEngine", meta: WeightUpdateMeta):
        """Wire an inference engine for weight updates + rollout dispatch."""
        raise NotImplementedError()

    def set_version(self, version: int) -> None:
        raise NotImplementedError()

    def get_version(self) -> int:
        raise NotImplementedError()

    def save(self, meta: SaveLoadMeta) -> None:
        raise NotImplementedError()

    def load(self, meta: SaveLoadMeta) -> None:
        raise NotImplementedError()

    def step_lr_scheduler(self) -> None:
        """Advance the LR schedule one step (no-op when the schedule is
        driven by the optimizer step count, the optax default)."""

    # -- compute --------------------------------------------------------
    def train_batch(
        self,
        input_: dict[str, Any],
        loss_fn: Callable[[Any, dict[str, Any]], Any],
        loss_weight_fn: Callable[[dict[str, Any]], Any],
    ) -> dict[str, float]:
        """One optimizer step over a padded batch, internally split into
        FFD-balanced packed micro-batches. loss_fn consumes packed 1-D
        inputs; loss_weight_fn supplies each micro-batch's weight for global
        loss normalization."""
        raise NotImplementedError()

    def eval_batch(
        self,
        input_: dict[str, Any],
        loss_fn: Callable[[Any, dict[str, Any]], Any],
        loss_weight_fn: Callable[[dict[str, Any]], Any],
    ):
        raise NotImplementedError()

    def forward(
        self,
        input_: dict[str, Any],
        output_seqlens: list[int] | None = None,
        post_hook: Callable[[Any, dict[str, Any]], Any] | None = None,
        aggregate_fn: Callable[[list[Any]], Any] | None = None,
    ):
        """Gradient-free forward over micro-batches; results are un-padded,
        re-ordered to input order, and aggregated."""
        raise NotImplementedError()


class InferenceEngine(abc.ABC):
    """Rollout/generation engine contract (parity: engine_api.py:347)."""

    def initialize(
        self,
        addr: str | None = None,
        ft_spec: FinetuneSpec | None = None,
        train_data_parallel_size: int | None = None,
    ):
        raise NotImplementedError()

    def destroy(self):
        pass

    # -- generation -----------------------------------------------------
    async def agenerate(self, req: ModelRequest) -> ModelResponse:
        """Asynchronously generate a response for one request."""
        raise NotImplementedError()

    # -- rollout queue --------------------------------------------------
    def submit(
        self,
        data: dict[str, Any],
        workflow: "RolloutWorkflow | None" = None,
        workflow_builder: Callable | None = None,
        should_accept: Callable | None = None,
    ) -> None:
        raise NotImplementedError()

    def wait(self, count: int, timeout: float | None = None) -> dict[str, Any]:
        raise NotImplementedError()

    def rollout_batch(
        self,
        data: list[dict[str, Any]],
        workflow: "RolloutWorkflow | None" = None,
        workflow_builder: Callable | None = None,
        should_accept: Callable | None = None,
    ) -> dict[str, Any]:
        raise NotImplementedError()

    def prepare_batch(
        self,
        dataloader,
        workflow: "RolloutWorkflow | None" = None,
        workflow_builder: Callable | None = None,
        should_accept: Callable | None = None,
    ) -> dict[str, Any]:
        raise NotImplementedError()

    # -- flow control ---------------------------------------------------
    def pause(self):
        """Stop submitting new rollouts (weight-update window)."""
        raise NotImplementedError()

    def resume(self):
        raise NotImplementedError()

    def pause_generation(self):
        """Interrupt in-flight generation on the servers."""

    def continue_generation(self):
        pass

    # -- weight updates -------------------------------------------------
    def init_weights_update_group(self, meta: WeightUpdateMeta):
        pass

    def update_weights_from_distributed(self, meta: WeightUpdateMeta, *args, **kwargs):
        raise NotImplementedError()

    def update_weights_from_disk(self, meta: WeightUpdateMeta):
        raise NotImplementedError()

    def update_weights_from_tensor(
        self,
        named: dict,
        version: int | None = None,
        chunk_mb: float = 512,
        **kwargs,
    ) -> None:
        """Install host tensors keyed by `/`-joined param-tree path (the
        "dcn" in-memory push; see areal_tpu/core/weight_transfer.py).
        `named` may also be an iterable of (name, array) pairs for
        pipelined producers. Implementations may accept `lora_scale` (LoRA
        delta push) and `overlap`/`inflight` (staged-push controls)."""
        raise NotImplementedError()

    # -- staged weight sync (optional; transports with a stage/commit
    #    split — the HTTP "dcn" path — implement these so staging overlaps
    #    live generation and only the commit pays a pause) ---------------
    def stage_weights(
        self,
        named,
        push_id: str | None = None,
        chunk_mb: float = 512,
        inflight: int | None = None,
    ) -> str:
        """Stream weight buckets into server-side staging WITHOUT pausing
        generation; returns the push_id to commit or abort."""
        raise NotImplementedError()

    def commit_staged(
        self,
        push_id: str,
        version: int | None = None,
        lora_scale: float | None = None,
    ) -> None:
        """Atomically install the staged weights (the only pause window)."""
        raise NotImplementedError()

    def abort_push(self, push_id: str) -> None:
        """Drop server-side staging for a failed/abandoned push."""
        raise NotImplementedError()

    def set_version(self, version: int) -> None:
        raise NotImplementedError()

    def get_version(self) -> int:
        raise NotImplementedError()
