"""Reward function plumbing (parity: areal/api/reward_api.py).

`AsyncRewardWrapper` turns a synchronous reward function (rule-based math
verification, sandboxed code execution, ...) into an awaitable that runs in
a thread pool with a timeout, so slow verifier calls never stall the rollout
event loop.
"""

from __future__ import annotations

import asyncio
import functools
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable

from areal_tpu.utils import logging

logger = logging.getLogger("reward_api")

_DEFAULT_POOL: ThreadPoolExecutor | None = None


def _pool() -> ThreadPoolExecutor:
    global _DEFAULT_POOL
    if _DEFAULT_POOL is None:
        _DEFAULT_POOL = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="reward"
        )
    return _DEFAULT_POOL


# Positional parameters of the reward-fn contract
# (prompt, completion, prompt_ids, completion_ids). Dataset items carrying
# same-named keys (a "prompt" text field is common) must be filtered from the
# **kwargs or the call raises TypeError("got multiple values") — which the
# wrapper's failure path would silently turn into 0 reward.
REWARD_POSITIONAL = (
    "prompt",
    "completion",
    "completions",
    "prompt_ids",
    "completion_ids",
)


def reward_kwargs(data: dict) -> dict:
    return {k: v for k, v in data.items() if k not in REWARD_POSITIONAL}


class AsyncRewardWrapper:
    """Wrap a sync reward fn into an async callable with timeout.

    The wrapped function signature follows the reference convention:
    reward_fn(prompt, completion, prompt_ids, completion_ids, **data) -> float
    """

    def __init__(
        self,
        reward_fn: Callable[..., float],
        timeout_seconds: float = 15.0,
        executor: ThreadPoolExecutor | None = None,
    ):
        self.reward_fn = reward_fn
        self.timeout_seconds = timeout_seconds
        self.executor = executor

    async def __call__(self, *args: Any, **kwargs: Any) -> float:
        loop = asyncio.get_running_loop()
        fn = functools.partial(self.reward_fn, *args, **kwargs)
        try:
            return float(
                await asyncio.wait_for(
                    loop.run_in_executor(self.executor or _pool(), fn),
                    timeout=self.timeout_seconds,
                )
            )
        except asyncio.TimeoutError:
            logger.warning(
                f"reward fn {getattr(self.reward_fn, '__name__', '?')} timed "
                f"out after {self.timeout_seconds}s; returning 0"
            )
            return 0.0
        except Exception as e:  # noqa: BLE001
            logger.warning(f"reward fn raised {e!r}; returning 0")
            return 0.0
