"""Core IO dataclasses shared across the framework.

Parity target: areal/api/io_struct.py:21-231 (ModelRequest/ModelResponse/
FinetuneSpec/ParamSpec/WeightUpdateMeta/SaveLoadMeta/RolloutStat/StepInfo).
TPU changes: `WeightUpdateMeta.type` gains "memory" (same-process device_put
resharding, the colocated fast path) and "dcn" (cross-pod transfer server)
alongside "disk"; dtype sizes come from numpy instead of torch.
"""

from __future__ import annotations

import os
import uuid
from dataclasses import dataclass, field
from typing import Any, Literal

import numpy as np

from areal_tpu.api.cli_args import GenerationHyperparameters


@dataclass
class ModelRequest:
    rid: str = field(default_factory=lambda: str(uuid.uuid4()))
    input_ids: list[int] = field(default_factory=list)
    gconfig: GenerationHyperparameters = field(
        default_factory=GenerationHyperparameters
    )
    metadata: dict[str, Any] = field(default_factory=dict)
    tokenizer: Any = None
    # VLM inputs: list of images (bytes/base64/PIL), passed through to the
    # decode backend (parity: io_struct.py:21 ModelRequest.image_data).
    image_data: list[Any] | None = None

    def copy(self) -> "ModelRequest":
        return ModelRequest(
            rid=self.rid,
            input_ids=list(self.input_ids),
            gconfig=self.gconfig.new(),
            metadata=dict(self.metadata),
            tokenizer=self.tokenizer,
            image_data=list(self.image_data) if self.image_data else None,
        )


@dataclass
class ModelResponse:
    input_tokens: list[int] = field(default_factory=list)
    output_tokens: list[int] = field(default_factory=list)
    output_logprobs: list[float] = field(default_factory=list)
    # Weight version that produced each output token — the heart of the
    # async/staleness bookkeeping (reference io_struct.py:48).
    output_versions: list[int] = field(default_factory=list)
    stop_reason: Literal["length", "stop", "interrupt"] = "stop"
    tokenizer: Any = None

    # statistics
    latency: float = float("inf")
    ttft: float = float("inf")
    itl: list[float] = field(default_factory=list)

    @property
    def input_len(self) -> int:
        return len(self.input_tokens)

    @property
    def output_len(self) -> int:
        return len(self.output_tokens)


@dataclass
class FinetuneSpec:
    total_train_epochs: int
    dataset_size: int
    train_batch_size: int

    @property
    def total_train_steps(self) -> int:
        return self.total_train_epochs * (self.dataset_size // self.train_batch_size)

    @property
    def steps_per_epoch(self) -> int:
        return self.dataset_size // self.train_batch_size


@dataclass
class ParamSpec:
    name: str
    shape: tuple
    dtype: str

    @property
    def size(self) -> int:
        """Param bytes."""
        return int(np.dtype(_np_dtype(self.dtype)).itemsize * np.prod(self.shape))


def _np_dtype(dtype: str) -> str:
    # numpy has no bfloat16; it is 2 bytes like float16 for sizing purposes.
    return {"bfloat16": "float16"}.get(dtype, dtype)


@dataclass
class WeightUpdateMeta:
    """How trainer weights reach the decode engine.

    - "memory": colocated — the trainer hands sharded jax.Arrays to the decode
      engine which `device_put`s them onto its own sharding. Zero-copy when
      shardings agree; the TPU analogue of the reference NCCL broadcast.
    - "disk": save HF-format safetensors shards + name_resolve timestamp
      handshake (identical semantics to the reference's fallback path).
    - "dcn": cross-slice transfer server (learner pod → decode pod).
    """

    type: Literal["disk", "memory", "dcn"] = "memory"
    path: str | None = None
    alloc_mode: Any = None
    transfer_addr: str | None = None
    transfer_port: int = 29500
    group_name: str = "update_weight_group"
    weight_chunked_mem_mb: int = 1024
    use_lora: bool = False

    @classmethod
    def from_disk(
        cls,
        experiment_name: str,
        trial_name: str,
        file_root: str,
        name: str = "default",
        use_lora: bool = False,
    ) -> "WeightUpdateMeta":
        path = os.path.join(
            file_root,
            "checkpoints",
            experiment_name,
            trial_name,
            name,
            "weight_update",
        )
        return cls(type="disk", path=path, use_lora=use_lora)

    @classmethod
    def from_memory(cls, alloc_mode: Any = None) -> "WeightUpdateMeta":
        return cls(type="memory", alloc_mode=alloc_mode)


@dataclass
class HttpRequest:
    endpoint: str
    payload: dict[str, Any]
    method: str = "POST"


@dataclass
class HttpGenerationResult:
    output_tokens: list[int]
    output_logprobs: list[float]
    stop_reason: str


@dataclass
class SaveLoadMeta:
    path: str
    weight_format: str = "hf"  # "hf" (safetensors) | "orbax"
    with_optim: bool = False
    tokenizer: Any = None
    base_model_path: str | None = None


@dataclass
class RolloutStat:
    submitted: int = 0
    accepted: int = 0
    running: int = 0


@dataclass
class StepInfo:
    epoch: int
    epoch_step: int
    global_step: int
    steps_per_epoch: int

    def next(self) -> "StepInfo":
        last_in_epoch = self.epoch_step == self.steps_per_epoch - 1
        return StepInfo(
            epoch=self.epoch + last_in_epoch,
            epoch_step=0 if last_in_epoch else self.epoch_step + 1,
            global_step=self.global_step + 1,
            steps_per_epoch=self.steps_per_epoch,
        )
