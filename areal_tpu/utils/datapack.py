"""Sequence-length-balanced partitioning and bin packing.

Parity target: areal/utils/datapack.py — `ffd_allocate` (first-fit-decreasing
bin packing under a token budget, :187), `partition_balanced` (:14),
`min_abs_diff_partition` (:77), `flat2d` (:9). These are host-side
routines that drive micro-batch splitting and cross-DP rollout
redistribution; they never run on device.

The two loops that scale with the rollout batch (FFD over thousands of
sequences per PPO step; the O(n²k) partition DP) run through the C++
kernels in csrc/datapack.cc (ctypes, built on demand — the reference
compiles the same loops with numba). The numpy implementations below are
the behavioral spec and the fallback when no compiler is available;
semantics are identical and tested equal.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "flat2d",
    "partition_balanced",
    "min_abs_diff_partition",
    "ffd_allocate",
    "reorder_to_balanced_batches",
]


def flat2d(arr: list[list]) -> list:
    """Flatten one nesting level."""
    return [x for sub in arr for x in sub]


def partition_balanced(nums: np.ndarray, k: int, min_size: int = 1) -> list[list[int]]:
    """Partition the *ordered* sequence `nums` into `k` contiguous pieces
    minimising the maximum piece sum (each piece ≥ min_size elements).

    Dynamic programming over prefix sums, O(n²k). C++ fast path
    (csrc/datapack.cc::partition_balanced_native); numpy DP fallback.
    Returns index lists per piece.
    """
    nums = np.asarray(nums, dtype=np.int64)
    n = len(nums)
    if k <= 0 or n < k * min_size:
        raise ValueError(f"cannot split {n} items into {k} parts of >= {min_size}")

    from areal_tpu.utils._native import load_datapack

    lib = load_datapack()
    if lib is not None:
        import ctypes

        arr = np.ascontiguousarray(nums)
        bounds = np.zeros(k + 1, dtype=np.int64)
        rc = lib.partition_balanced_native(
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n,
            k,
            min_size,
            bounds.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        if rc == 0:
            return [
                list(range(int(bounds[j]), int(bounds[j + 1])))
                for j in range(k)
            ]
    prefix = np.concatenate([[0], np.cumsum(nums)])

    # dp[j][i]: minimal max-sum splitting the first i items into j pieces.
    INF = float("inf")
    dp = np.full((k + 1, n + 1), INF)
    choice = np.zeros((k + 1, n + 1), dtype=np.int64)
    dp[0][0] = 0.0
    for j in range(1, k + 1):
        for i in range(j * min_size, n + 1):
            # last piece covers (t, i]
            for t in range((j - 1) * min_size, i - min_size + 1):
                cand = max(dp[j - 1][t], prefix[i] - prefix[t])
                if cand < dp[j][i]:
                    dp[j][i] = cand
                    choice[j][i] = t
    # reconstruct
    bounds = [n]
    i = n
    for j in range(k, 0, -1):
        i = int(choice[j][i])
        bounds.append(i)
    bounds.reverse()
    return [list(range(bounds[j], bounds[j + 1])) for j in range(k)]


def min_abs_diff_partition(nums: np.ndarray, k: int) -> list[tuple[int, int]]:
    """Split ordered `nums` into `k` contiguous spans with minimal max-sum;
    returns (start, end) bounds per span (parity: datapack.py:77)."""
    parts = partition_balanced(np.asarray(nums), k)
    return [(p[0], p[-1] + 1) for p in parts]


def ffd_allocate(
    values: list[int], capacity: int, min_groups: int = 1
) -> list[list[int]]:
    """First-fit-decreasing bin packing: group indices of `values` into bins
    whose sums stay ≤ capacity, producing at least `min_groups` bins.

    The workhorse behind micro-batch allocation and cross-DP rebalancing
    (parity: datapack.py:187). Items larger than `capacity` get singleton
    bins (the caller is expected to have filtered or to accept overflow).
    """
    values = list(values)
    if capacity <= 0:
        raise ValueError("capacity must be positive")

    from areal_tpu.utils._native import load_datapack

    lib = load_datapack()
    if lib is not None and values:
        import ctypes

        arr = np.ascontiguousarray(np.asarray(values, dtype=np.int64))
        bin_of = np.zeros(len(values), dtype=np.int32)
        n_bins = int(
            lib.ffd_allocate_native(
                arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                len(values),
                capacity,
                bin_of.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            )
        )
        bins = [[] for _ in range(n_bins)]
        for i, b in enumerate(bin_of):
            bins[int(b)].append(i)
        # Restore FFD insertion order (desc value, ties by index) so the
        # min_groups splitting in _finish_ffd cuts bins exactly where the
        # pure-python path would — native and fallback stay bit-identical.
        bins = [sorted(b, key=lambda i: (-values[i], i)) for b in bins]
        bin_sums = [int(arr[b].astype(np.int64).sum()) for b in bins]
        return _finish_ffd(values, bins, bin_sums, min_groups)

    order = sorted(range(len(values)), key=lambda i: values[i], reverse=True)
    bins: list[list[int]] = []
    bin_sums: list[int] = []
    for idx in order:
        v = values[idx]
        placed = False
        for b in range(len(bins)):
            if bin_sums[b] + v <= capacity:
                bins[b].append(idx)
                bin_sums[b] += v
                placed = True
                break
        if not placed:
            bins.append([idx])
            bin_sums.append(v)
    return _finish_ffd(values, bins, bin_sums, min_groups)


def _finish_ffd(
    values: list[int],
    bins: list[list[int]],
    bin_sums: list[int],
    min_groups: int,
) -> list[list[int]]:
    # Meet the minimum group count by splitting the largest bins.
    while len(bins) < min_groups:
        # pick the bin with most items that can be split
        cand = max(
            (b for b in range(len(bins)) if len(bins[b]) > 1),
            key=lambda b: bin_sums[b],
            default=None,
        )
        if cand is None:
            # all singletons; pad with empty bins
            bins.append([])
            bin_sums.append(0)
            continue
        items = bins[cand]
        half = len(items) // 2
        bins[cand] = items[:half]
        bin_sums[cand] = sum(values[i] for i in items[:half])
        bins.append(items[half:])
        bin_sums.append(sum(values[i] for i in items[half:]))
    # Keep deterministic order: sort each bin's indices, sort bins by first idx.
    bins = [sorted(b) for b in bins]
    bins.sort(key=lambda b: (b[0] if b else 1 << 60))
    return bins


def reorder_to_balanced_batches(
    seqlens: np.ndarray, batch_size_per_chunk: int
) -> list[list[int]]:
    """Greedy longest-first round-robin into fixed-size chunks so each chunk
    has a similar token total (parity: datapack.py:117)."""
    order = np.argsort(-np.asarray(seqlens))
    n_chunks = int(np.ceil(len(order) / batch_size_per_chunk))
    chunks: list[list[int]] = [[] for _ in range(n_chunks)]
    sums = np.zeros(n_chunks, dtype=np.int64)
    for idx in order:
        # place into the least-loaded chunk with room
        cand = None
        for c in np.argsort(sums):
            if len(chunks[c]) < batch_size_per_chunk:
                cand = int(c)
                break
        chunks[cand].append(int(idx))
        sums[cand] += seqlens[idx]
    return [sorted(c) for c in chunks if c]
