"""Analytic per-chip HBM accounting for train + decode plans.

The reference sizes its allocations by operator experience (blog's 7B
recipe pins d16t4+d8t4 on H800s); on TPU we can do better: the GSPMD
engine's memory layout is regular enough to predict in closed form, so an
allocation plan can be *validated* against the target chip's HBM before
anything is launched (AllocationMode.check_hbm). The model:

Per chip, a training step holds
  params        n_params x param_bytes / (pp * dp * tp)     [ZeRO-3 + TP]
  grads         n_params x param_bytes / (pp * dp * tp)     [same sharding]
  opt (adamw)   2 x n_params x 4      / (pp * dp * tp)      [f32 mu + nu]
  activations   under full remat, only per-layer boundaries are saved:
                (L/pp) x T_local x d x act_bytes
                plus ONE layer's recompute working set
                T_local x (3d + 2ff/tp + 2*nH*hd/tp) x act_bytes
  logits        fused vocab-chunked head: T_local x chunk x 4;
                unfused: T_local x V x 4  (f32 logits)
  pp stash      1f1b keeps (2*pp-1) stage inputs alive between a
                microbatch's forward and backward; the interleaved
                schedule v*(2*pp-1) virtual-chunk inputs — each entry
                T_local x d x act_bytes

With ZeRO-1 (`zero1=True`, `fsdp=False`) the f32 AdamW moments divide by
dp even though params/grads replicate; the per-chip bytes that sharding
frees are surfaced as `opt_freed_bytes` / `zero1_freed_gib`.

where T_local = per-chip microbatch tokens (dp and sp shard the token
axis; pp processes one microbatch per stage at a time). Without remat the
activation term multiplies by the ~10 saved tensors per layer instead of 1.

A decode server holds
  params        n_params x param_bytes / tp
  kv pool       2 x (L ) x pool_tokens x nKV x hd x kv_bytes / tp

Known-good anchor (unit-tested): Qwen2.5-0.5B = 0.494e9 params; the
estimator's activation model is cross-checked against XLA's own
`compile().memory_analysis()` on a tiny mesh in tests/test_hbm.py.

HBM capacities are per-chip device specs (public): v5e 16 GiB, v5p 95 GiB,
v4 32 GiB, v6e 32 GiB.
"""

from __future__ import annotations

from dataclasses import dataclass

GiB = 1024**3

# Per-chip HBM by NORMALIZED device-kind substring (first match wins).
# Normalization strips spaces/dashes/underscores so every spelling of the
# v5e family ("TPU v5 lite", "tpu-v5-lite-podslice", "v5litepod") hits the
# 16 GiB row — a substring match on the raw string would fall through to
# the plain-"v5" (v5p) row and credit a 16 GiB chip with 95 GiB.
HBM_BYTES: tuple[tuple[str, int], ...] = (
    ("v6", 32 * GiB),
    ("v5lite", 16 * GiB),
    ("v5e", 16 * GiB),
    ("v5", 95 * GiB),  # v5p reports plain "TPU v5"
    ("v4", 32 * GiB),
)


def _normalize_kind(device_kind: str) -> str:
    return (
        device_kind.lower().replace(" ", "").replace("-", "").replace("_", "")
    )


def hbm_bytes(device_kind: str) -> int:
    kind = _normalize_kind(device_kind)
    for sub, b in HBM_BYTES:
        if sub in kind:
            return b
    return 16 * GiB  # conservative default


def _dtype_bytes(dtype) -> int:
    s = str(dtype)
    if "64" in s:
        return 8
    if "32" in s:
        return 4
    if "16" in s:
        return 2
    if "8" in s:
        return 1
    raise ValueError(f"unrecognized dtype {dtype!r}")


def param_count(cfg) -> int:
    """Exact decoder parameter count for models/qwen2.py's layout."""
    d = cfg.hidden_size
    nH = cfg.num_attention_heads
    nKV = cfg.num_key_value_heads
    hd = d // nH
    L = cfg.num_hidden_layers
    V = cfg.vocab_size

    attn = d * (nH + 2 * nKV) * hd + nH * hd * d
    if getattr(cfg, "qkv_bias", True):
        attn += (nH + 2 * nKV) * hd
    if getattr(cfg, "attn_out_bias", False):
        attn += d
    n_experts = getattr(cfg, "num_experts", 0) or 0
    if n_experts:
        ff = getattr(cfg, "moe_intermediate_size", None) or cfg.intermediate_size
        mlp = n_experts * 3 * d * ff + d * n_experts  # experts + router
        shared = getattr(cfg, "shared_expert_intermediate_size", 0) or 0
        if shared:
            mlp += 3 * d * shared + d  # shared expert + its gate
    else:
        mlp = 3 * d * cfg.intermediate_size
    norms = 2 * d
    per_layer = attn + mlp + norms
    embed = V * d
    head = 0 if getattr(cfg, "tie_word_embeddings", False) else V * d
    return L * per_layer + embed + head + d  # + final norm


def wq_elem_counts(cfg) -> tuple[int, int]:
    """(quantizable kernel elements, scale elements) for int8 weight
    serving, mirroring models/qwen2's layer map (_WQ_ATTN_AXES /
    _WQ_MLP_AXES): the dense attn + mlp matmul kernels quantize with one
    f32 scale per output channel; MoE mlp subtrees (router-marked) stay
    fp — their attn kernels still quantize — as do embed, lm_head, norms,
    biases and LoRA adapters."""
    d = cfg.hidden_size
    nH = cfg.num_attention_heads
    nKV = cfg.num_key_value_heads
    hd = d // nH
    L = cfg.num_hidden_layers
    q = d * (nH + 2 * nKV) * hd + nH * hd * d  # q/k/v + o kernels
    s = (nH + 2 * nKV) * hd + d  # one scale per output channel
    if not (getattr(cfg, "num_experts", 0) or 0):
        ff = cfg.intermediate_size
        q += 3 * d * ff  # gate + up + down
        s += 2 * ff + d
    return L * q, L * s


@dataclass
class HBMEstimate:
    params_bytes: int
    grads_bytes: int
    opt_bytes: int
    activation_bytes: int
    logits_bytes: int
    kv_bytes: int = 0
    # pipeline stash: the 1f1b schedules keep stage (or virtual-chunk)
    # inputs alive between forward and backward — 2*pp-1 entries for plain
    # 1f1b, v*(2*pp-1) for the interleaved schedule
    stash_bytes: int = 0
    # informational: bytes the ZeRO-1 dp-sharded optimizer update freed
    # per chip vs a dp-replicated opt state (already subtracted from
    # opt_bytes; NOT part of total_bytes)
    opt_freed_bytes: int = 0
    # informational: bytes int8 weight serving freed per chip vs the fp
    # kernels (already subtracted from params_bytes; NOT part of
    # total_bytes) — headroom a fixed HBM budget can hand to the KV pool
    weight_freed_bytes: int = 0

    @property
    def total_bytes(self) -> int:
        return (
            self.params_bytes
            + self.grads_bytes
            + self.opt_bytes
            + self.activation_bytes
            + self.logits_bytes
            + self.kv_bytes
            + self.stash_bytes
        )

    def breakdown(self) -> dict:
        out = {
            "params_gib": round(self.params_bytes / GiB, 3),
            "grads_gib": round(self.grads_bytes / GiB, 3),
            "opt_gib": round(self.opt_bytes / GiB, 3),
            "activations_gib": round(self.activation_bytes / GiB, 3),
            "logits_gib": round(self.logits_bytes / GiB, 3),
            "kv_gib": round(self.kv_bytes / GiB, 3),
            "stash_gib": round(self.stash_bytes / GiB, 3),
            "total_gib": round(self.total_bytes / GiB, 3),
        }
        if self.opt_freed_bytes:
            out["zero1_freed_gib"] = round(self.opt_freed_bytes / GiB, 3)
        if self.weight_freed_bytes:
            out["wquant_freed_gib"] = round(self.weight_freed_bytes / GiB, 3)
        return out


def estimate_train_hbm(
    model_cfg,
    *,
    dp: int = 1,
    tp: int = 1,
    pp: int = 1,
    sp: int = 1,
    microbatch_tokens: int = 8192,
    remat: bool = True,
    fused_lm_head: bool = True,
    vocab_chunk: int = 8192,
    optimizer: str = "adamw",
    fsdp: bool = True,
    zero1: bool = False,
    pipeline_schedule: str = "1f1b",
    virtual_pp: int = 1,
) -> HBMEstimate:
    """Per-chip peak HBM for one training step of the GSPMD engine.

    `microbatch_tokens` is the GLOBAL token count of one microbatch (the
    unit `train_batch` runs per dispatch); dp and sp shard it.

    Sharding regimes: `fsdp=True` dp-shards params, grads AND opt state
    (the ZeRO-3-ish default the estimator has always priced). With
    `fsdp=False`, params/grads replicate over dp; `zero1=True` then still
    dp-shards the f32 AdamW moments (jax.zero1_optimizer's reduce-scatter
    / sharded-update / all-gather step) — `opt_freed_bytes` records what
    that sharding saved per chip vs a replicated opt state.

    Pipelining: for pp>1 the 1f1b schedules stash stage inputs between a
    microbatch's forward and its backward — 2*pp-1 entries for "1f1b",
    `virtual_pp`*(2*pp-1) *chunk* inputs for "1f1b_interleaved" (each 1/v
    the layers but a full [T_local, d] activation, so the stash bytes grow
    ~v times while the bubble shrinks ~1/v: that trade is exactly what
    bench --mode ppsched measures).
    """
    n = param_count(model_cfg)
    pbytes = _dtype_bytes(getattr(model_cfg, "param_dtype", "float32"))
    abytes = _dtype_bytes(getattr(model_cfg, "dtype", "bfloat16"))
    shard = (dp if fsdp else 1) * tp * pp
    opt_shard = (dp if (fsdp or zero1) else 1) * tp * pp
    d = model_cfg.hidden_size
    nH = model_cfg.num_attention_heads
    hd = d // nH
    ff = model_cfg.intermediate_size
    L = model_cfg.num_hidden_layers

    t_local = max(1, microbatch_tokens // (dp * sp))
    layers_local = max(1, L // pp)
    boundary = layers_local * t_local * d * abytes
    # one decoder layer's live intermediates during (re)computation: qkv
    # streams + two ff intermediates + attn scores working set, tp-sharded
    working = t_local * (3 * d + (2 * ff + 2 * nH * hd) // tp) * abytes
    if remat:
        act = boundary + working
    else:
        # ~10 saved tensors per layer (qkv, probs-free flash residuals,
        # ff gate/up, norms) — the classic no-remat multiplier
        act = boundary * 10 + working
    if fused_lm_head:
        logits = t_local * min(vocab_chunk, model_cfg.vocab_size) * 4
    else:
        logits = t_local * model_cfg.vocab_size * 4
    stash = 0
    if pp > 1 and pipeline_schedule in ("1f1b", "1f1b_interleaved"):
        v = virtual_pp if pipeline_schedule == "1f1b_interleaved" else 1
        stash = v * (2 * pp - 1) * t_local * d * abytes
    opt_mult = 2 if optimizer == "adamw" else 0  # f32 mu + nu
    opt = opt_mult * n * 4 // opt_shard
    opt_freed = 0
    if zero1 and not fsdp and dp > 1:
        opt_freed = opt_mult * n * 4 // (tp * pp) - opt
    return HBMEstimate(
        params_bytes=n * pbytes // shard,
        grads_bytes=n * pbytes // shard,
        opt_bytes=opt,
        activation_bytes=act,
        logits_bytes=logits,
        stash_bytes=stash,
        opt_freed_bytes=opt_freed,
    )


def estimate_decode_hbm(
    model_cfg,
    *,
    tp: int = 1,
    pool_tokens: int | None = None,
    slots: int = 64,
    context_length: int = 32768,
    kv_cache_dtype: str = "bfloat16",
    weight_dtype: str = "fp",
) -> HBMEstimate:
    """Per-chip HBM for a decode server: tp-sharded params + paged KV pool.

    `pool_tokens=None` models dense provisioning (slots x context) — the
    difference vs a sized pool is exactly what the paged cache buys.

    `weight_dtype="int8"` (JaxDecodeConfig.weight_dtype) prices the dense
    matmul kernels at 1 byte/element plus one f32 scale per output channel
    instead of param_dtype; the per-chip bytes that frees vs fp serving
    surface as `wquant_freed_gib` in breakdown() — at a fixed HBM budget
    that headroom goes to a larger resident KV pool (bench --mode wquant).
    """
    n = param_count(model_cfg)
    pbytes = _dtype_bytes(getattr(model_cfg, "param_dtype", "bfloat16"))
    kvb = _dtype_bytes(kv_cache_dtype)
    d = model_cfg.hidden_size
    hd = d // model_cfg.num_attention_heads
    nKV = max(model_cfg.num_key_value_heads, tp)  # GQA heads repeat to tp
    if pool_tokens is None:
        pool_tokens = slots * context_length
    kv = 2 * model_cfg.num_hidden_layers * pool_tokens * nKV * hd * kvb // tp
    params_bytes = n * pbytes // tp
    weight_freed = 0
    if weight_dtype == "int8":
        nq, ns = wq_elem_counts(model_cfg)
        quantized = ((n - nq) * pbytes + nq * 1 + ns * 4) // tp
        weight_freed = params_bytes - quantized
        params_bytes = quantized
    elif weight_dtype != "fp":
        raise ValueError(f"weight_dtype={weight_dtype!r} not in ('fp', 'int8')")
    return HBMEstimate(
        params_bytes=params_bytes,
        grads_bytes=0,
        opt_bytes=0,
        activation_bytes=0,
        logits_bytes=0,
        kv_bytes=kv,
        weight_freed_bytes=weight_freed,
    )


def check_fit(
    estimate: HBMEstimate,
    device_kind: str,
    *,
    utilization: float = 0.9,
) -> None:
    """Raise if the plan cannot fit the chip (90% of HBM usable by default:
    XLA needs headroom for fusion temporaries and the compiled program)."""
    cap = int(hbm_bytes(device_kind) * utilization)
    if estimate.total_bytes > cap:
        raise MemoryError(
            f"plan needs {estimate.total_bytes / GiB:.2f} GiB/chip but "
            f"{device_kind!r} offers {cap / GiB:.2f} GiB usable "
            f"({utilization:.0%} of {hbm_bytes(device_kind) / GiB:.0f} GiB): "
            f"{estimate.breakdown()}"
        )
