"""Denominator-conditioned distributed statistics tracker.

Parity target: areal/utils/stats_tracker.py:30 (DistributedStatsTracker) —
hierarchical scopes, bool-mask denominators, AVG/SUM/MIN/MAX/AVG_MIN_MAX
reductions, `record_timing` wall-clock scopes, and an `export()` that reduces
across the data-parallel group.

TPU adaptation: values are numpy/jax arrays instead of torch tensors, and the
cross-host reduction happens through an optional `reduce_fn(dict) -> dict`
hook (wired to `jax.experimental.multihost_utils` by the train engine) rather
than a torch.distributed group — inside a single JAX process, per-chip stats
are already globally consistent because SPMD computations produce replicated
results.
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager
from enum import Enum, auto
from threading import Lock

import numpy as np


class ReduceType(Enum):
    AVG_MIN_MAX = auto()
    AVG = auto()
    SUM = auto()
    MIN = auto()
    MAX = auto()
    SCALAR = auto()


def _to_numpy(x) -> np.ndarray:
    return np.asarray(x)


class DistributedStatsTracker:
    def __init__(self, name: str = ""):
        self.lock = Lock()
        self.scope_stack: list[str] = []
        if name:
            self.scope_stack.append(name.strip("/"))
        self.denominators: dict[str, str] = {}
        self.reduce_types: dict[str, ReduceType] = {}
        self.stats: dict[str, list] = defaultdict(list)
        # Per-stat snapshot of the denominator array current at stat() time,
        # so numerators always pair with the mask they were recorded under.
        self._denom_snapshots: dict[str, list] = defaultdict(list)

    # -- scoping --------------------------------------------------------
    def scope(self, name: str):
        return self.Scope(self, name)

    class Scope:
        def __init__(self, tracker, name):
            self.tracker = tracker
            self.name = name.strip("/")

        def __enter__(self):
            self.tracker.scope_stack.append(self.name)
            return self

        def __exit__(self, exc_type, exc_val, exc_tb):
            self.tracker.scope_stack.pop()

    def _full_key(self, key: str) -> str:
        if not self.scope_stack:
            return key
        return "/".join(self.scope_stack + [key])

    @contextmanager
    def disable_scope(self):
        tmp, self.scope_stack = self.scope_stack, []
        try:
            yield
        finally:
            self.scope_stack = tmp

    # -- recording ------------------------------------------------------
    @contextmanager
    def record_timing(self, key: str):
        start = time.perf_counter()
        try:
            yield
        finally:
            with self.lock:
                full_key = f"timeperf/{key}"
                self._set_reduce_type(full_key, ReduceType.SCALAR)
                self.stats[full_key].append(time.perf_counter() - start)

    def denominator(self, **kwargs):
        with self.lock:
            for key, value in kwargs.items():
                arr = _to_numpy(value)
                if arr.dtype != np.bool_:
                    raise ValueError(f"`{key}` must be a bool array, got {arr.dtype}")
                if arr.size == 0:
                    raise ValueError(f"`{key}` must be non-empty")
                full_key = self._full_key(key)
                self._set_reduce_type(full_key, ReduceType.SUM)
                self.stats[full_key].append(arr)

    def scalar(self, **kwargs):
        with self.lock:
            for key, value in kwargs.items():
                full_key = self._full_key(key)
                self._set_reduce_type(full_key, ReduceType.SCALAR)
                self.stats[full_key].append(float(value))

    def stat(
        self,
        denominator: str,
        reduce_type: ReduceType | None = None,
        **kwargs,
    ):
        with self.lock:
            for key, value in kwargs.items():
                arr = _to_numpy(value).astype(np.float32)
                if arr.size == 0:
                    raise ValueError(f"`{key}` should be non-empty")
                if reduce_type == ReduceType.SCALAR:
                    raise ValueError("cannot use SCALAR reduce type for an array")
                full_key = self._full_key(key)
                denom_key = self._full_key(denominator)
                if denom_key not in self.stats:
                    raise ValueError(
                        f"denominator `{denom_key}` does not exist; record it first"
                    )
                denom = self.stats[denom_key][-1]
                if denom.shape != arr.shape:
                    raise ValueError(
                        f"shape mismatch between `{full_key}` {arr.shape} and "
                        f"denominator `{denom_key}` {denom.shape}"
                    )
                self.denominators[full_key] = denom_key
                if reduce_type is not None:
                    self._set_reduce_type(full_key, reduce_type)
                elif full_key not in self.reduce_types:
                    self._set_reduce_type(full_key, ReduceType.AVG_MIN_MAX)
                self.stats[full_key].append(arr)
                self._denom_snapshots[full_key].append(denom)

    def _set_reduce_type(self, key: str, reduce_type: ReduceType):
        if not isinstance(reduce_type, ReduceType):
            raise ValueError("reduce type must be a ReduceType enum")
        self.reduce_types[key] = reduce_type

    # -- export ---------------------------------------------------------
    def export(self, key=None, reduce_fn=None, reset=True) -> dict[str, float]:
        """Aggregate recorded stats into a flat {key: float} dict.

        `reduce_fn` (optional) receives the aggregated dict and may perform a
        cross-host reduction, returning the reduced dict.
        """
        with self.lock:
            if key is not None:
                keys = [k for k in self.stats if k == key or k.startswith(key + "/")]
            else:
                keys = list(self.stats.keys())
            result: dict[str, float] = {}
            for k in sorted(keys):
                result.update(self._aggregate(k))
            if reset:
                for k in keys:
                    del self.stats[k]
                    self._denom_snapshots.pop(k, None)
        if reduce_fn is not None:
            result = reduce_fn(result)
        return result

    def _aggregate(self, key: str) -> dict[str, float]:
        values = self.stats[key]
        if not values:
            return {}
        rt = self.reduce_types.get(key, ReduceType.AVG_MIN_MAX)
        if rt == ReduceType.SCALAR:
            return {key: float(np.mean(values))}

        xs = values
        if key in self._denom_snapshots and self._denom_snapshots[key]:
            denoms = [d.astype(np.float32) for d in self._denom_snapshots[key]]
        else:
            denoms = [np.ones_like(v) for v in values]

        total_num = sum(float(d.sum()) for d in denoms)
        out: dict[str, float] = {}
        if rt in (ReduceType.AVG, ReduceType.AVG_MIN_MAX):
            total = sum(float((x * d).sum()) for x, d in zip(xs, denoms))
            out[key if rt == ReduceType.AVG else f"{key}/avg"] = (
                total / total_num if total_num > 0 else 0.0
            )
        if rt in (ReduceType.MIN, ReduceType.AVG_MIN_MAX):
            mins = [
                float(np.where(d > 0, x, np.inf).min())
                for x, d in zip(xs, denoms)
                if d.sum() > 0
            ]
            if mins:
                out[key if rt == ReduceType.MIN else f"{key}/min"] = min(mins)
        if rt in (ReduceType.MAX, ReduceType.AVG_MIN_MAX):
            maxs = [
                float(np.where(d > 0, x, -np.inf).max())
                for x, d in zip(xs, denoms)
                if d.sum() > 0
            ]
            if maxs:
                out[key if rt == ReduceType.MAX else f"{key}/max"] = max(maxs)
        if rt == ReduceType.SUM:
            out[key] = sum(float(x.sum()) for x in xs)
        return out


# -- module-level default tracker (parity: stats_tracker.get/export_all) ----
_trackers: dict[str, DistributedStatsTracker] = {}


def get(name: str = "") -> DistributedStatsTracker:
    if name not in _trackers:
        _trackers[name] = DistributedStatsTracker(name)
    return _trackers[name]


DEFAULT = get()


def scope(name):
    return DEFAULT.scope(name)


def record_timing(key):
    return DEFAULT.record_timing(key)


def denominator(**kwargs):
    return DEFAULT.denominator(**kwargs)


def scalar(**kwargs):
    return DEFAULT.scalar(**kwargs)


def stat(denominator: str, reduce_type: ReduceType | None = None, **kwargs):
    return DEFAULT.stat(denominator, reduce_type, **kwargs)


def export_all(reduce_fn=None, reset=True) -> dict[str, float]:
    return DEFAULT.export(reduce_fn=reduce_fn, reset=reset)
