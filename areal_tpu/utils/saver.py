"""Freq-gated model checkpointing.

Parity target: areal/utils/saver.py:12 (Saver) — periodic HF-format saves
under {fileroot}/checkpoints/{experiment}/{trial}/{name}/epoch{E}epochstep{S}globalstep{G}.
"""

from __future__ import annotations

import os

from areal_tpu.api.cli_args import SaverConfig
from areal_tpu.api.io_struct import FinetuneSpec, SaveLoadMeta
from areal_tpu.utils import logging
from areal_tpu.utils.timeutil import FrequencyControl

logger = logging.getLogger("saver")


class Saver:
    def __init__(
        self, config: SaverConfig, ft_spec: FinetuneSpec, for_recover: bool = False
    ):
        self.config = config
        self.ft_spec = ft_spec
        self.for_recover = for_recover
        self.freq_ctl = FrequencyControl(
            freq_epoch=config.freq_epochs,
            freq_step=config.freq_steps,
            freq_sec=config.freq_secs,
        )
        # periodic-save failures observed this process (a full disk or a
        # flaky store must not kill the training loop; see save())
        self.save_failures = 0

    @staticmethod
    def get_save_checkpoint_root(config: SaverConfig, name: str = "default") -> str:
        return os.path.join(
            config.fileroot,
            "checkpoints",
            config.experiment_name,
            config.trial_name,
            name,
        )

    @staticmethod
    def get_save_checkpoint_path(
        config: SaverConfig,
        epoch: int,
        step: int,
        global_step: int,
        name: str = "default",
    ) -> str:
        path = os.path.join(
            Saver.get_save_checkpoint_root(config, name),
            f"epoch{epoch}epochstep{step}globalstep{global_step}",
        )
        os.makedirs(path, exist_ok=True)
        return path

    def save(
        self,
        engine,
        epoch: int,
        step: int,
        global_step: int,
        name: str = "default",
        tokenizer=None,
        base_model_path: str | None = None,
        force: bool = False,
    ) -> str | None:
        """Save if a frequency gate fires (or `force`); returns the path
        saved to, else None."""
        if not force and not self.freq_ctl.check(
            epochs=int(step == self.ft_spec.steps_per_epoch - 1), steps=1
        ):
            return None
        path = self.get_save_checkpoint_path(
            self.config, epoch, step, global_step, name
        )
        try:
            engine.save(
                SaveLoadMeta(
                    path=path,
                    weight_format="hf",
                    with_optim=self.for_recover,
                    tokenizer=tokenizer,
                    base_model_path=base_model_path,
                )
            )
        except Exception as e:  # noqa: BLE001 — degrade like RecoverHandler.dump
            self.save_failures += 1
            logger.error(
                f"checkpoint save failed at global_step {global_step} "
                f"({e!r}); retrying at the next frequency gate "
                f"(failures so far: {self.save_failures})"
            )
            return None
        logger.info(f"saved checkpoint at global_step {global_step} -> {path}")
        return path

    def state_dict(self) -> dict:
        return self.freq_ctl.state_dict()

    def load_state_dict(self, state: dict) -> None:
        self.freq_ctl.load_state_dict(state)
