"""Matmul-FLOPs accounting for decoder LMs + TPU peak-FLOPs table.

The reference reports training throughput as tokens consumed per step and
derives TFLOP/s / MFU offline (realhf/system/master_worker.py:497-533 logs
`time_perf/e2e` + `n_tokens`; benchmark/.../README.md:33-43 parses them).
Here the FLOPs model is explicit so the train engine can emit TFLOP/s live:

Per-token *forward* matmul FLOPs (2·m·n per [m,n] matmul output element):
  per layer:   qkv proj        2·d·(nH + 2·nKV)·hd
               attn out proj   2·nH·hd·d
               scores + values 4·ctx·nH·hd        (ctx = avg causal context)
               gate/up/down    6·d·ff             (SwiGLU: three matmuls)
  final:       lm_head         2·d·V

Embedding *lookup* is a gather, not a matmul, and is excluded — but the
lm_head projection is a real matmul and is counted (once, even when tied).
Backward re-does each matmul twice (dX and dW) → train = 3× forward.
MoE: ff work is per-activated-expert (top_k), not per-parameter.
"""

from __future__ import annotations


# bf16 peak FLOP/s per chip by NORMALIZED device-kind substring (first
# match wins; normalization strips spaces/dashes/underscores so GKE-style
# spellings like "tpu-v5-lite-podslice" don't fall through to the v5p row).
PEAK_FLOPS: tuple[tuple[str, float], ...] = (
    ("v6", 918e12),
    ("v5lite", 197e12),
    ("v5e", 197e12),
    ("v5", 459e12),  # v5p reports plain "TPU v5"
    ("v4", 275e12),
)


def peak_flops(device_kind: str) -> float:
    kind = (
        device_kind.lower().replace(" ", "").replace("-", "").replace("_", "")
    )
    for sub, f in PEAK_FLOPS:
        if sub in kind:
            return f
    return 100e12  # unknown accelerator / CPU: nominal figure


def forward_flops_per_token(model_cfg, avg_context: float) -> float:
    """Forward matmul FLOPs per token.

    `model_cfg` is areal_tpu.models.qwen2.ModelConfig (duck-typed: needs
    hidden_size, intermediate_size, num_hidden_layers, num_attention_heads,
    num_key_value_heads, vocab_size, and optionally num_experts/
    num_experts_per_tok/moe_intermediate_size).

    `avg_context` is the mean number of kv positions each query attends to;
    for full causal self-attention over length-L sequences this is ~L/2.
    """
    d = model_cfg.hidden_size
    nH = model_cfg.num_attention_heads
    nKV = model_cfg.num_key_value_heads
    hd = d // nH
    L = model_cfg.num_hidden_layers

    qkv = 2 * d * (nH + 2 * nKV) * hd
    out = 2 * nH * hd * d
    attn = 4 * avg_context * nH * hd
    n_experts = getattr(model_cfg, "num_experts", 0) or 0
    if n_experts:
        ff = getattr(model_cfg, "moe_intermediate_size", None) or (
            model_cfg.intermediate_size
        )
        top_k = getattr(model_cfg, "num_experts_per_tok", 1) or 1
        mlp = 6 * d * ff * top_k + 2 * d * n_experts  # experts + router
    else:
        mlp = 6 * d * model_cfg.intermediate_size
    lm_head = 2 * d * model_cfg.vocab_size
    return L * (qkv + out + attn + mlp) + lm_head


def train_flops_per_token(model_cfg, avg_context: float) -> float:
    """Fwd + bwd matmul FLOPs per trained token (bwd = 2x fwd)."""
    return 3.0 * forward_flops_per_token(model_cfg, avg_context)
