"""Batch container utilities: padded ⇄ packed dict-of-arrays conversions,
micro-batch splitting, and reward/advantage normalization.

Parity target: areal/utils/data.py (concat_padded_tensors :152,
pack_tensor_dict :266, split_padded_tensor_dict_into_mb_list :404,
pad_packed_tensor_dict :524, Normalization :1073, KLEstimator :1306).

TPU-first design notes
----------------------
- A "batch" is a plain dict[str, np.ndarray] on host. Padded layout is
  [B, T] with an `attention_mask`; packed layout is 1-D `input_ids` plus
  `cu_seqlens` (int32, [n+1]) — the layout the segment-aware Pallas/GAE
  kernels consume.
- XLA compiles one program per shape. `pad_packed_tensor_dict` therefore pads
  the packed stream to a *bucketed* length (pad_to_multiple) so repeated
  training steps reuse the compiled executable instead of recompiling
  (reference pads for CUDA alignment; here it is a compile-cache contract).
- The reference's broadcast/all_gather "tensor container" helpers move data
  between torch ranks; under a single SPMD program the same role is played
  by `jax.make_array_from_process_local_data` / host-local sharding, see
  areal_tpu/parallel/. Host-side helpers here stay framework-free numpy.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from areal_tpu.api.cli_args import MicroBatchSpec, NormConfig
from areal_tpu.utils import datapack

__all__ = [
    "get_batch_size",
    "dict_map",
    "dict_of_list2list_of_dict",
    "list_of_dict2dict_of_list",
    "pad_sequences_to_tensors",
    "concat_padded_tensors",
    "pack_tensor_dict",
    "unpack_sequence",
    "pad_packed_tensor_dict",
    "unpad_logits",
    "MicroBatchList",
    "split_padded_tensor_dict_into_mb_list",
    "amend_position_ids",
    "zigzag_indices",
    "zigzag_inverse_indices",
    "Normalization",
    "KLEstimator",
    "cycle_dataloader",
]


def get_batch_size(data: dict[str, Any]) -> int:
    for v in data.values():
        if isinstance(v, np.ndarray) and v.ndim >= 1:
            return v.shape[0]
    raise ValueError("cannot infer batch size from empty dict")


def dict_map(x: dict, fn: Callable) -> dict:
    return {k: fn(v) for k, v in x.items()}


def dict_of_list2list_of_dict(d: dict[str, list]) -> list[dict]:
    if not d:
        return []
    n = len(next(iter(d.values())))
    assert all(len(v) == n for v in d.values())
    return [{k: v[i] for k, v in d.items()} for i in range(n)]


def list_of_dict2dict_of_list(lst: list[dict]) -> dict[str, list]:
    if not lst:
        return {}
    keys = lst[0].keys()
    assert all(x.keys() == keys for x in lst)
    return {k: [x[k] for x in lst] for k in keys}


def pad_sequences_to_tensors(
    sequences: list[dict[str, Any]], pad_value: float = 0.0
) -> dict[str, np.ndarray]:
    """Stack a list of variable-length 1-D sample dicts into padded [B, T]
    arrays + attention_mask (parity: data.py:82)."""
    if not sequences:
        return {}
    max_len = max(len(seq["input_ids"]) for seq in sequences)
    out: dict[str, list] = {}
    for seq in sequences:
        seq_len = len(seq["input_ids"])
        for k, v in seq.items():
            arr = np.asarray(v)
            if arr.ndim >= 1 and arr.shape[0] == seq_len:
                pad_width = [(0, max_len - seq_len)] + [(0, 0)] * (arr.ndim - 1)
                padded = np.pad(arr, pad_width, constant_values=pad_value)
            else:
                padded = arr
            out.setdefault(k, []).append(padded)
        mask = np.zeros(max_len, dtype=bool)
        mask[:seq_len] = True
        out.setdefault("attention_mask", []).append(mask)
    return {k: np.stack(v) for k, v in out.items()}


def concat_padded_tensors(
    tensor_dicts: list[dict[str, np.ndarray]], pad_value: float = 0.0
) -> dict[str, np.ndarray]:
    """Concatenate padded batches along the batch dim, re-padding every
    sequence-shaped array to the common max length (parity: data.py:152)."""
    if not tensor_dicts:
        return {}
    max_len = max(d["attention_mask"].shape[1] for d in tensor_dicts)
    keys = tensor_dicts[0].keys()
    assert all(d.keys() == keys for d in tensor_dicts), "inconsistent batch keys"
    out: dict[str, list] = {k: [] for k in keys}
    for d in tensor_dicts:
        cur_len = d["attention_mask"].shape[1]
        for k, v in d.items():
            v = np.asarray(v)
            if v.ndim >= 2 and v.shape[1] == cur_len:
                pad_width = [(0, 0), (0, max_len - cur_len)] + [(0, 0)] * (v.ndim - 2)
                fill = 0 if k == "attention_mask" else pad_value
                v = np.pad(v, pad_width, constant_values=fill)
            out[k].append(v)
    return {k: np.concatenate(v, axis=0) for k, v in out.items()}


def pack_tensor_dict(data: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    """Padded [B, T] → packed 1-D layout with cu_seqlens (parity: data.py:266).

    Sequence-shaped values (shape [B, T, ...]) are flattened to
    [total_tokens, ...]; everything else passes through. Adds `cu_seqlens`
    (int32 [B+1]) and `max_seqlen` (python int).
    """
    mask = data["attention_mask"].astype(bool)
    bsz, _ = mask.shape
    lens = mask.sum(axis=1).astype(np.int32)
    cu_seqlens = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
    out: dict[str, Any] = {}
    for k, v in data.items():
        if k == "attention_mask":
            continue
        v = np.asarray(v)
        if v.ndim >= 2 and v.shape[:2] == mask.shape:
            out[k] = v[mask]
        else:
            out[k] = v
    out["cu_seqlens"] = cu_seqlens
    out["max_seqlen"] = int(lens.max()) if bsz else 0
    return out


def unpack_sequence(
    packed: np.ndarray, cu_seqlens: np.ndarray
) -> list[np.ndarray]:
    """Packed 1-D array → list of per-sequence arrays (parity: data.py:224)."""
    return [
        packed[cu_seqlens[i] : cu_seqlens[i + 1]] for i in range(len(cu_seqlens) - 1)
    ]


def pad_packed_tensor_dict(
    data: dict[str, Any],
    pad_to_length: int | None = None,
    pad_to_multiple: int = 128,
    pad_token: int = 0,
) -> tuple[dict[str, Any], int]:
    """Pad a packed batch's token stream to a bucketed static length.

    Appends one fake sequence of padding tokens (extra cu_seqlens entry) so
    segment-aware kernels treat the tail as a separate masked-out sequence.
    Returns (padded_dict, pad_len). The bucketing (pad_to_multiple, default
    128 = one TPU lane tile) is what keeps XLA's compile cache warm across
    steps with varying token counts (parity: data.py:524).
    """
    cu_seqlens = data["cu_seqlens"]
    total = int(cu_seqlens[-1])
    if pad_to_length is None:
        pad_to_length = ((total + pad_to_multiple - 1) // pad_to_multiple) * pad_to_multiple
        pad_to_length = max(pad_to_length, pad_to_multiple)
    if pad_to_length < total:
        raise ValueError(f"pad_to_length {pad_to_length} < total tokens {total}")
    pad_len = pad_to_length - total
    out: dict[str, Any] = {}
    for k, v in data.items():
        if k == "cu_seqlens":
            out[k] = (
                np.concatenate([cu_seqlens, [pad_to_length]]).astype(np.int32)
                if pad_len > 0
                else cu_seqlens
            )
        elif k == "max_seqlen":
            out[k] = max(int(v), pad_len)
        elif isinstance(v, np.ndarray) and v.ndim >= 1 and v.shape[0] == total:
            pad_width = [(0, pad_len)] + [(0, 0)] * (v.ndim - 1)
            value = pad_token if k == "input_ids" else 0
            out[k] = np.pad(v, pad_width, constant_values=value)
        else:
            out[k] = v
    return out, pad_len


def unpad_logits(logits: np.ndarray, pad_len: int) -> np.ndarray:
    """Drop the tail introduced by pad_packed_tensor_dict (data.py:756)."""
    if pad_len == 0:
        return logits
    return logits[:-pad_len]


def zigzag_indices(total: int, n_shards: int) -> np.ndarray:
    """Zig-zag context-parallel permutation for a length-`total` token axis.

    View the axis as 2n chunks of total/(2n) tokens; shard i holds the
    chunk pair (i, 2n-1-i), so under causal attention every shard does the
    same work (the head of the stream pairs with the tail) — the classic
    balanced CP layout (Megatron/TransformerEngine zig-zag;
    ops/ring_attention.py consumes it via explicit global positions).

    Returns `perm` with perm[j] = original index of the token placed at
    permuted position j; apply as `x_zigzag = x[perm]`.
    """
    assert total % (2 * n_shards) == 0, (total, n_shards)
    c = total // (2 * n_shards)
    chunks = np.arange(total, dtype=np.int32).reshape(2 * n_shards, c)
    order = []
    for i in range(n_shards):
        order.append(chunks[i])
        order.append(chunks[2 * n_shards - 1 - i])
    return np.concatenate(order)


def zigzag_inverse_indices(total: int, n_shards: int) -> np.ndarray:
    """Inverse of `zigzag_indices`: contiguous = permuted[inv]."""
    perm = zigzag_indices(total, n_shards)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(total, dtype=np.int32)
    return inv


def amend_position_ids(data: dict[str, Any]) -> dict[str, Any]:
    """Add per-sequence position_ids to a packed batch (data.py:823)."""
    cu = data["cu_seqlens"]
    total = int(cu[-1])
    pos = np.arange(total, dtype=np.int32)
    starts = np.repeat(cu[:-1], np.diff(cu))
    data = dict(data)
    data["position_ids"] = pos - starts.astype(np.int32)
    return data


@dataclass
class MicroBatchList:
    """A padded batch split into packed micro-batches (data.py:358)."""

    data: dict[str, Any]
    mbs: list[dict[str, Any]]
    # forward/backward index maps: sample indices of the original batch per mb
    group_lens: list[int] = field(default_factory=list)
    forward_indices: list[list[int]] = field(default_factory=list)
    padded_to: list[int] = field(default_factory=list)

    def __len__(self) -> int:
        return len(self.mbs)


def split_padded_tensor_dict_into_mb_list(
    data: dict[str, np.ndarray],
    mb_spec: MicroBatchSpec,
    pad_to_multiple: int = 128,
) -> MicroBatchList:
    """Split a padded batch into FFD-balanced packed micro-batches under a
    token budget (parity: data.py:404).

    Groups of `mb_spec.granularity` adjacent samples (GRPO groups) stay
    together. Each micro-batch is packed (1-D + cu_seqlens) and padded to a
    bucketed length for XLA compile-cache reuse.
    """
    mask = data["attention_mask"].astype(bool)
    bsz = mask.shape[0]
    g = max(mb_spec.granularity, 1)
    if bsz % g != 0:
        raise ValueError(f"batch size {bsz} not divisible by granularity {g}")
    group_lens = mask.reshape(bsz // g, g, -1).sum(axis=(1, 2)).astype(np.int64)

    if mb_spec.max_tokens_per_mb is not None:
        capacity = mb_spec.max_tokens_per_mb
    else:
        capacity = int(group_lens.sum()) + 1  # single bin unless n_mbs forces more
    min_groups = mb_spec.n_mbs or 1
    bins = datapack.ffd_allocate(list(group_lens), capacity, min_groups=min_groups)

    mbs, fwd_indices, padded_to = [], [], []
    for b in bins:
        sample_idx = datapack.flat2d([list(range(gi * g, (gi + 1) * g)) for gi in b])
        sub = {k: np.asarray(v)[sample_idx] for k, v in data.items()
               if isinstance(v, np.ndarray) and v.ndim >= 1 and v.shape[0] == bsz}
        packed = pack_tensor_dict(sub)
        packed, pad_len = pad_packed_tensor_dict(packed, pad_to_multiple=pad_to_multiple)
        mbs.append(packed)
        fwd_indices.append(sample_idx)
        padded_to.append(pad_len)
    return MicroBatchList(
        data=data,
        mbs=mbs,
        group_lens=[int(x) for x in group_lens],
        forward_indices=fwd_indices,
        padded_to=padded_to,
    )


# ---------------------------------------------------------------------------
# Normalization & KL estimation (host-side numpy; parity data.py:1073,1306)
# ---------------------------------------------------------------------------


class Normalization:
    """Adaptive reward/advantage normalization with independent mean/std
    levels ("batch" | "group" | None), leave-one-out means, and unbiased std.

    Under SPMD the "all-reduce across DP" of the reference is unnecessary:
    normalization runs on the host over the *global* batch before dispatch.
    """

    def __init__(self, config: NormConfig):
        if config.mean_level not in {"batch", "group", None}:
            raise ValueError(f"bad mean_level {config.mean_level}")
        if config.std_level not in {"batch", "group", None}:
            raise ValueError(f"bad std_level {config.std_level}")
        self.mean_level = config.mean_level
        self.mean_leave1out = config.mean_leave1out
        self.std_level = config.std_level
        self.std_unbiased = config.std_unbiased
        self.group_size = config.group_size
        self.eps = config.eps

    def __call__(
        self, x: np.ndarray, loss_mask: np.ndarray | None = None
    ) -> np.ndarray:
        x = np.asarray(x, dtype=np.float64)
        if loss_mask is not None:
            loss_mask = np.asarray(loss_mask, dtype=np.float64)
            if loss_mask.sum() == 0:
                return x.astype(np.float32)

        mean = self._mean_at_level(x, loss_mask)
        x_centered = x - mean
        if loss_mask is not None:
            x_centered = x_centered * loss_mask

        if self.std_level is None:
            std, eps = np.ones_like(x), 0.0
        else:
            std, eps = self._std_at_level(x, loss_mask, mean), self.eps
        return (x_centered / (std + eps)).astype(np.float32)

    # mean ---------------------------------------------------------------
    def _mean_at_level(self, x, mask):
        if self.mean_level is None:
            return np.zeros_like(x)
        if self.mean_level == "batch":
            return self._mean(x, mask, self.mean_leave1out)
        out = np.zeros_like(x)
        bs = x.shape[0]
        for i in range(bs // self.group_size):
            s = slice(i * self.group_size, (i + 1) * self.group_size)
            m = mask[s] if mask is not None else None
            if self.group_size == 1 and self.mean_leave1out:
                out[s] = 0.0
            else:
                out[s] = self._mean(x[s], m, self.mean_leave1out)
        return out

    @staticmethod
    def _mean(x, mask, leave_one_out):
        if mask is None:
            factor = x.size
            x_masked = x
        else:
            x_masked = x * mask
            factor = mask.sum()
        total = x_masked.sum()
        if leave_one_out:
            if factor <= 1:
                return np.zeros_like(x)
            if mask is None:
                return (total - x) / (factor - 1)
            loo = (total - x_masked) / np.clip(factor - mask, 1.0, None)
            return np.where(mask > 0, loo, total / factor)
        if factor == 0:
            return np.zeros_like(x)
        return np.full_like(x, total / factor)

    # std ----------------------------------------------------------------
    def _std_at_level(self, x, mask, mean):
        if self.std_level == "batch":
            return self._std(x, mask, mean, self.std_unbiased)
        out = np.zeros_like(x)
        bs = x.shape[0]
        for i in range(bs // self.group_size):
            s = slice(i * self.group_size, (i + 1) * self.group_size)
            m = mask[s] if mask is not None else None
            if self.group_size == 1 and self.std_unbiased:
                out[s] = 1.0
            else:
                out[s] = self._std(x[s], m, mean[s], self.std_unbiased)
        return out

    @staticmethod
    def _std(x, mask, mean, unbiased):
        if mask is None:
            factor = x.size
            centered = x - mean
        else:
            factor = mask.sum()
            centered = x * mask - mean * mask
        ssq = (centered**2).sum()
        if unbiased:
            if factor <= 1:
                return np.ones_like(x)
            return np.full_like(x, np.sqrt(ssq / (factor - 1)))
        if factor == 0:
            return np.ones_like(x)
        return np.full_like(x, np.sqrt(ssq / factor))


class KLEstimator:
    """Schulman k1/k2/k3 approximate KL (data.py:1306; joschu.net/blog/kl-approx)."""

    def __init__(self, kl_estimator: str = "k1", apply_clamp: bool = True):
        if kl_estimator not in ("k1", "k2", "k3"):
            raise ValueError(f"invalid KL estimator {kl_estimator}")
        self.kl_estimator = kl_estimator
        self.apply_clamp = apply_clamp

    def __call__(self, log_probs, log_probs_base):
        # Works on numpy and jax arrays alike (pure elementwise ops).
        lr = log_probs - log_probs_base
        if self.kl_estimator == "k2":
            lr = lr**2 / 2.0
        elif self.kl_estimator == "k3":
            neg = -lr
            lr = np.exp(neg) - 1 - neg if isinstance(lr, np.ndarray) else _jexp(neg) - 1 - neg
        if self.apply_clamp:
            lr = lr.clip(-10.0, 10.0)
        return lr


def _jexp(x):
    import jax.numpy as jnp

    return jnp.exp(x)


def cycle_dataloader(dataloader):
    """Infinite iterator over a (re-shuffling) dataloader (data.py:1063)."""
    while True:
        yield from dataloader
