"""Async HTTP helpers for the rollout control plane.

Parity target: areal/utils/http.py (arequest_with_retry over aiohttp with
per-endpoint retries and pooled connectors). The decode-server protocol is
JSON-over-HTTP exactly like the reference's SGLang/vLLM control plane; only
the payload schema differs (see areal_tpu/launcher/decode_server.py).
"""

from __future__ import annotations

import asyncio
import weakref
from typing import Any

import aiohttp

DEFAULT_RETRIES = 3
DEFAULT_REQUEST_TIMEOUT = 3600.0


class HttpRequestError(Exception):
    def __init__(self, message: str, status: int | None = None):
        super().__init__(message)
        self.status = status


# One pooled ClientSession per event loop. aiohttp sessions are bound to the
# loop that created them; the rollout executor runs its own background loop
# and short-lived `asyncio.run` loops appear for fanout RPCs, so key weakly
# by the loop object (id()-keying would alias dead loops on address reuse).
_sessions: "weakref.WeakKeyDictionary[asyncio.AbstractEventLoop, aiohttp.ClientSession]" = (
    weakref.WeakKeyDictionary()
)


def _get_session() -> aiohttp.ClientSession:
    loop = asyncio.get_running_loop()
    sess = _sessions.get(loop)
    if sess is None or sess.closed:
        # No session-level total timeout: callers pass per-request timeouts
        # (the session is shared by short health probes and hour-long
        # generations on the same loop).
        sess = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=None, sock_connect=30),
            connector=aiohttp.TCPConnector(limit=0, ttl_dns_cache=300),
        )
        _sessions[loop] = sess
    return sess


async def close_current_session() -> None:
    """Close the pooled session of the running loop (call before the loop
    exits in short-lived `asyncio.run` scopes to avoid leaking sockets)."""
    loop = asyncio.get_running_loop()
    sess = _sessions.pop(loop, None)
    if sess is not None and not sess.closed:
        await sess.close()


async def arequest_with_retry(
    addr: str,
    endpoint: str,
    payload: dict[str, Any] | None = None,
    method: str = "POST",
    max_retries: int = DEFAULT_RETRIES,
    timeout: float = DEFAULT_REQUEST_TIMEOUT,
    retry_delay: float = 1.0,
    data: bytes | None = None,
) -> dict[str, Any]:
    """POST/GET `http://{addr}{endpoint}`, return parsed JSON; retry on
    connection errors and 5xx. 4xx raise immediately. `data` sends a raw
    binary body instead of JSON (weight-transfer buckets)."""
    last_exc: Exception | None = None
    url = f"http://{addr}{endpoint}"
    for attempt in range(max_retries):
        try:
            session = _get_session()
            async with session.request(
                method,
                url,
                json=payload if method != "GET" and data is None else None,
                data=data,
                timeout=aiohttp.ClientTimeout(total=timeout, sock_connect=30),
            ) as resp:
                if resp.status >= 400:
                    raise HttpRequestError(
                        f"{url} -> {resp.status}: {await resp.text()}",
                        status=resp.status,
                    )
                return await resp.json()
        except (aiohttp.ClientError, asyncio.TimeoutError, HttpRequestError) as e:
            if (
                isinstance(e, HttpRequestError)
                and e.status is not None
                and e.status < 500
            ):
                raise
            last_exc = e
            if attempt + 1 < max_retries:
                await asyncio.sleep(retry_delay * (2**attempt))
    raise HttpRequestError(
        f"request to {url} failed after {max_retries} retries"
    ) from last_exc


async def aget_with_retry(
    addr: str, endpoint: str, **kw: Any
) -> dict[str, Any]:
    return await arequest_with_retry(addr, endpoint, method="GET", **kw)


async def wait_server_healthy(
    addr: str, timeout: float = 120.0, interval: float = 1.0
) -> None:
    """Poll GET /health until it returns 200 or `timeout` elapses."""
    deadline = asyncio.get_running_loop().time() + timeout
    while True:
        try:
            await arequest_with_retry(
                addr, "/health", method="GET", max_retries=1, timeout=10
            )
            return
        except Exception:
            if asyncio.get_running_loop().time() > deadline:
                raise TimeoutError(f"server {addr} not healthy after {timeout}s")
            await asyncio.sleep(interval)
