"""Async HTTP helpers for the rollout control plane.

Parity target: areal/utils/http.py (arequest_with_retry over aiohttp with
per-endpoint retries and pooled connectors). The decode-server protocol is
JSON-over-HTTP exactly like the reference's SGLang/vLLM control plane; only
the payload schema differs (see areal_tpu/launcher/decode_server.py).

Robustness semantics (ISSUE 9):
- Error responses carry their parsed JSON body on `HttpRequestError.body`
  so callers read structured fields (`retry_after`, `reason`) instead of
  regexing a stringified exception.
- Retry backoff is jittered (uniform [1-j, 1+j] scale) so synchronized
  clients don't retry in lockstep.
- A torn/truncated response body (JSON parse failure on a 2xx) is a
  RETRYABLE transport error, not a crash — the server's reply was lost in
  transit; the retry (same xid) is deduplicated server-side.
- Fault-injection seams: `client.http.send` (before the request leaves —
  an abort is a clean pre-effect loss), `client.http.recv` (after a 2xx
  arrived — an abort is the error-after-effect shape), `client.http.body`
  (torn payloads).
"""

from __future__ import annotations

import asyncio
import json
import random
import weakref
from typing import Any

import aiohttp

from areal_tpu.core import fault_injection

DEFAULT_RETRIES = 3
DEFAULT_REQUEST_TIMEOUT = 3600.0


class HttpRequestError(Exception):
    def __init__(
        self,
        message: str,
        status: int | None = None,
        body: dict[str, Any] | None = None,
    ):
        super().__init__(message)
        self.status = status
        # parsed JSON error payload when the server sent one (structured
        # fields like retry_after live here, not in str(self))
        self.body = body or {}


# One pooled ClientSession per event loop. aiohttp sessions are bound to the
# loop that created them; the rollout executor runs its own background loop
# and short-lived `asyncio.run` loops appear for fanout RPCs, so key weakly
# by the loop object (id()-keying would alias dead loops on address reuse).
_sessions: "weakref.WeakKeyDictionary[asyncio.AbstractEventLoop, aiohttp.ClientSession]" = (
    weakref.WeakKeyDictionary()
)


def _get_session() -> aiohttp.ClientSession:
    loop = asyncio.get_running_loop()
    sess = _sessions.get(loop)
    if sess is None or sess.closed:
        # No session-level total timeout: callers pass per-request timeouts
        # (the session is shared by short health probes and hour-long
        # generations on the same loop).
        sess = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=None, sock_connect=30),
            connector=aiohttp.TCPConnector(limit=0, ttl_dns_cache=300),
        )
        _sessions[loop] = sess
    return sess


async def close_current_session() -> None:
    """Close the pooled session of the running loop (call before the loop
    exits in short-lived `asyncio.run` scopes to avoid leaking sockets)."""
    loop = asyncio.get_running_loop()
    sess = _sessions.pop(loop, None)
    if sess is not None and not sess.closed:
        await sess.close()


def _parse_json_body(text: str) -> dict[str, Any]:
    try:
        out = json.loads(text)
        return out if isinstance(out, dict) else {}
    except (ValueError, TypeError):
        return {}


def backoff_delays(
    base: float, retries: int, jitter: float = 0.25, cap: float = 60.0
):
    """Jittered exponential backoff generator: base·2^k scaled by
    uniform[1-jitter, 1+jitter], capped. Shared by the transport retry
    loop and the client's 429 honoring so every retry path in the stack
    desynchronizes the same way."""
    for attempt in range(retries):
        d = min(base * (2**attempt), cap)
        if jitter > 0.0:
            d *= 1.0 + random.uniform(-jitter, jitter)
        yield max(d, 0.0)


async def arequest_with_retry(
    addr: str,
    endpoint: str,
    payload: dict[str, Any] | None = None,
    method: str = "POST",
    max_retries: int = DEFAULT_RETRIES,
    timeout: float = DEFAULT_REQUEST_TIMEOUT,
    retry_delay: float = 1.0,
    data: bytes | None = None,
) -> dict[str, Any]:
    """POST/GET `http://{addr}{endpoint}`, return parsed JSON; retry on
    connection errors, 5xx, and torn (unparseable 2xx) responses. 4xx
    raise immediately with the parsed error body attached. `data` sends a
    raw binary body instead of JSON (weight-transfer buckets)."""
    last_exc: Exception | None = None
    url = f"http://{addr}{endpoint}"
    delays = backoff_delays(retry_delay, max_retries)
    inj = fault_injection.get()
    for attempt in range(max_retries):
        try:
            if inj is not None:
                await inj.afire(
                    "client.http.send",
                    addr=addr, endpoint=endpoint, method=method,
                    attempt=attempt,
                )
            session = _get_session()
            async with session.request(
                method,
                url,
                json=payload if method != "GET" and data is None else None,
                data=data,
                timeout=aiohttp.ClientTimeout(total=timeout, sock_connect=30),
            ) as resp:
                text = await resp.text()
                if resp.status >= 400:
                    raise HttpRequestError(
                        f"{url} -> {resp.status}: {text}",
                        status=resp.status,
                        body=_parse_json_body(text),
                    )
                if inj is not None:
                    # post-effect seam: the server processed the request
                    # and responded — a fault here loses only the reply
                    await inj.afire(
                        "client.http.recv",
                        addr=addr, endpoint=endpoint, method=method,
                        attempt=attempt,
                    )
                    text = inj.tear(
                        "client.http.body", text,
                        addr=addr, endpoint=endpoint,
                    )
                try:
                    return json.loads(text)
                except ValueError as e:
                    # torn response: the effect may have landed but the
                    # reply is unusable — retryable, idempotency dedups
                    raise HttpRequestError(
                        f"{url} -> torn response body "
                        f"({len(text)} bytes): {e}",
                        status=None,
                    ) from e
        except (
            aiohttp.ClientError,
            asyncio.TimeoutError,
            HttpRequestError,
            fault_injection.InjectedFault,
        ) as e:
            if (
                isinstance(e, HttpRequestError)
                and e.status is not None
                and e.status < 500
            ):
                raise
            last_exc = e
            if attempt + 1 < max_retries:
                await asyncio.sleep(next(delays))
    raise HttpRequestError(
        f"request to {url} failed after {max_retries} retries"
    ) from last_exc


async def aget_with_retry(
    addr: str, endpoint: str, **kw: Any
) -> dict[str, Any]:
    return await arequest_with_retry(addr, endpoint, method="GET", **kw)


async def wait_server_healthy(
    addr: str, timeout: float = 120.0, interval: float = 1.0
) -> None:
    """Poll GET /health until it returns 200 or `timeout` elapses."""
    deadline = asyncio.get_running_loop().time() + timeout
    while True:
        try:
            await arequest_with_retry(
                addr, "/health", method="GET", max_retries=1, timeout=10
            )
            return
        except Exception:
            if asyncio.get_running_loop().time() > deadline:
                raise TimeoutError(f"server {addr} not healthy after {timeout}s")
            await asyncio.sleep(interval)
