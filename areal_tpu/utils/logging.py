"""Structured, colored logging (parity: areal/utils/logging.py).

A thin wrapper over the stdlib logging module that gives every framework
module a consistent `[timestamp] [name] [level]` format, with ANSI colors
on TTYs and plain text otherwise.
"""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = "%(asctime)s.%(msecs)03d %(name)s %(levelname)s: %(message)s"
_DATE_FORMAT = "%Y%m%d-%H:%M:%S"

_COLORS = {
    "DEBUG": "\033[36m",  # cyan
    "INFO": "\033[32m",  # green
    "WARNING": "\033[33m",  # yellow
    "ERROR": "\033[31m",  # red
    "CRITICAL": "\033[41m",  # red background
}
_RESET = "\033[0m"


class _ColorFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        msg = super().format(record)
        color = _COLORS.get(record.levelname)
        if color and sys.stderr.isatty():
            return f"{color}{msg}{_RESET}"
        return msg


_configured = False


def _configure_root() -> None:
    global _configured
    if _configured:
        return
    handler = logging.StreamHandler(stream=sys.stderr)
    handler.setFormatter(_ColorFormatter(fmt=_FORMAT, datefmt=_DATE_FORMAT))
    root = logging.getLogger("areal_tpu")
    root.handlers.clear()
    root.addHandler(handler)
    root.setLevel(os.environ.get("AREAL_TPU_LOG_LEVEL", "INFO").upper())
    root.propagate = False
    _configured = True


def getLogger(name: str | None = None) -> logging.Logger:
    """Return a logger under the `areal_tpu` hierarchy."""
    _configure_root()
    if not name:
        return logging.getLogger("areal_tpu")
    return logging.getLogger(f"areal_tpu.{name}")
