"""Rank-0 metrics fan-out: console table + tensorboard (+ wandb/swanlab when
installed).

Parity target: areal/utils/stats_logger.py:20 (StatsLogger). wandb and
swanlab are optional — gated imports, "disabled" by default, matching the
reference's default modes.
"""

from __future__ import annotations

import os
from typing import Any

from areal_tpu.api.cli_args import StatsLoggerConfig
from areal_tpu.api.io_struct import FinetuneSpec
from areal_tpu.utils import logging

logger = logging.getLogger("stats_logger")


class StatsLogger:
    def __init__(self, config: StatsLoggerConfig, ft_spec: FinetuneSpec | None = None):
        self.config = config
        self.ft_spec = ft_spec
        self._tb_writer = None
        self._wandb = None
        self._swanlab = None
        self._init_backends()

    def _log_dir(self) -> str:
        return self.get_log_path(self.config)

    @staticmethod
    def get_log_path(config: StatsLoggerConfig) -> str:
        """Run log directory (parity: StatsLogger.get_log_path,
        areal/utils/stats_logger.py)."""
        return os.path.join(
            config.fileroot or "/tmp/areal_tpu",
            "logs",
            config.experiment_name,
            config.trial_name,
        )

    def _init_backends(self):
        cfg = self.config
        if cfg.tensorboard.path is not None:
            try:
                from tensorboardX import SummaryWriter

                self._tb_writer = SummaryWriter(logdir=cfg.tensorboard.path)
            except ImportError:
                logger.warning("tensorboardX not available; tensorboard disabled")
        if cfg.wandb.mode != "disabled":
            try:
                import wandb

                wandb.init(
                    mode=cfg.wandb.mode,
                    entity=cfg.wandb.entity,
                    project=cfg.wandb.project or cfg.experiment_name,
                    name=cfg.wandb.name or cfg.trial_name,
                    group=cfg.wandb.group,
                    notes=cfg.wandb.notes,
                    tags=cfg.wandb.tags,
                    config=cfg.wandb.config,
                )
                self._wandb = wandb
            except ImportError:
                logger.warning("wandb not installed; wandb logging disabled")
        if cfg.swanlab.mode not in (None, "disabled"):
            try:
                import swanlab

                if cfg.swanlab.api_key:
                    swanlab.login(cfg.swanlab.api_key)
                swanlab.init(
                    project=cfg.swanlab.project or cfg.experiment_name,
                    experiment_name=cfg.swanlab.name or cfg.trial_name,
                    config=cfg.swanlab.config,
                    logdir=cfg.swanlab.logdir,
                    mode=cfg.swanlab.mode,
                )
                self._swanlab = swanlab
            except ImportError:
                logger.warning("swanlab not installed; swanlab logging disabled")

    def commit(
        self, epoch: int, step: int, global_step: int, data: dict[str, Any]
    ) -> None:
        """Log one training step's stats to all backends + console. `data`
        may be one dict or a list of per-minibatch dicts (reference shape);
        keys appearing in several minibatch dicts log their MEAN across the
        step — last-write-wins would misreport e.g. `loss` as the final
        minibatch's value."""
        if isinstance(data, (list, tuple)):
            sums: dict[str, float] = {}
            counts: dict[str, int] = {}
            for d in data:
                for k, v in d.items():
                    sums[k] = sums.get(k, 0.0) + float(v)
                    counts[k] = counts.get(k, 0) + 1
            data = {k: sums[k] / counts[k] for k in sums}
        flat = {k: float(v) for k, v in data.items()}
        lines = [
            f"Epoch {epoch} step {step} (global step {global_step}):",
        ]
        width = max((len(k) for k in flat), default=0)
        for k in sorted(flat):
            lines.append(f"  {k:<{width}} = {flat[k]:.6g}")
        logger.info("\n".join(lines))
        if self._tb_writer is not None:
            for k, v in flat.items():
                self._tb_writer.add_scalar(k, v, global_step)
            self._tb_writer.flush()
        if self._wandb is not None:
            self._wandb.log(flat, step=global_step)
        if self._swanlab is not None:
            self._swanlab.log(flat, step=global_step)

    def close(self):
        if self._tb_writer is not None:
            self._tb_writer.close()
        if self._wandb is not None:
            self._wandb.finish()
        if self._swanlab is not None:
            self._swanlab.finish()
