"""Distributed key-value discovery service.

Parity: areal/utils/name_resolve.py (NameRecordRepository with Memory / NFS /
etcd3 / ray backends, TTL + keepalive threads, watch callbacks, reconfigure()).

The TPU build keeps the same contract with two always-available backends:

- ``MemoryNameRecordRepository`` — in-process dict; for single-process tests.
- ``NfsNameRecordRepository``    — one file per key under a shared filesystem
  root (NFS/GCS-fuse); the portable multi-host backend.

etcd3/ray backends from the reference are optional extras and are gated behind
imports (not available in this image).
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import threading
import time
import uuid
from pathlib import Path

from areal_tpu.utils import logging

logger = logging.getLogger("name_resolve")


class NameEntryExistsError(Exception):
    pass


class NameEntryNotFoundError(Exception):
    pass


@dataclasses.dataclass
class NameResolveConfig:
    """Mirror of reference NameResolveConfig (areal/api/cli_args.py:964)."""

    type: str = "nfs"  # "memory" | "nfs"
    nfs_record_root: str = "/tmp/areal_tpu/name_resolve"
    etcd3_addr: str = "localhost:2379"
    ray_actor_name: str = "name_resolve"


class NameRecordRepository:
    """Abstract name-record store. Keys are slash-separated paths."""

    def add(
        self,
        name: str,
        value: str,
        delete_on_exit: bool = True,
        keepalive_ttl: float | None = None,
        replace: bool = False,
    ) -> None:
        raise NotImplementedError()

    def get(self, name: str) -> str:
        raise NotImplementedError()

    def get_subtree(self, name_root: str) -> list[str]:
        """All values whose key is under `name_root`."""
        raise NotImplementedError()

    def find_subtree(self, name_root: str) -> list[str]:
        """All keys under `name_root` (sorted)."""
        raise NotImplementedError()

    def delete(self, name: str) -> None:
        raise NotImplementedError()

    def clear_subtree(self, name_root: str) -> None:
        raise NotImplementedError()

    def wait(
        self, name: str, timeout: float | None = None, poll_frequency: float = 0.1
    ) -> str:
        """Block until `name` appears, then return its value."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                return self.get(name)
            except NameEntryNotFoundError:
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(f"name_resolve.wait({name}) timed out")
                time.sleep(poll_frequency)

    def watch_names(
        self,
        names: list[str] | str,
        call_back,
        poll_frequency: float = 5.0,
        wait_timeout: float = 300.0,
    ) -> threading.Thread:
        """Invoke `call_back()` once any watched name disappears."""
        if isinstance(names, str):
            names = [names]

        def _watcher():
            for name in names:
                self.wait(name, timeout=wait_timeout)
            while True:
                try:
                    for name in names:
                        self.get(name)
                except NameEntryNotFoundError:
                    call_back()
                    return
                time.sleep(poll_frequency)

        t = threading.Thread(target=_watcher, daemon=True)
        t.start()
        return t

    def reset(self) -> None:
        """Remove all entries this process registered with delete_on_exit."""
        raise NotImplementedError()


class MemoryNameRecordRepository(NameRecordRepository):
    def __init__(self):
        self._store: dict[str, str] = {}
        self._lock = threading.Lock()
        self._owned: set[str] = set()

    def add(self, name, value, delete_on_exit=True, keepalive_ttl=None, replace=False):
        name = name.rstrip("/")
        with self._lock:
            if name in self._store and not replace:
                raise NameEntryExistsError(name)
            self._store[name] = str(value)
            if delete_on_exit:
                self._owned.add(name)

    def get(self, name):
        name = name.rstrip("/")
        with self._lock:
            if name not in self._store:
                raise NameEntryNotFoundError(name)
            return self._store[name]

    def get_subtree(self, name_root):
        prefix = name_root.rstrip("/")
        with self._lock:
            keys = sorted(
                k for k in self._store if k == prefix or k.startswith(prefix + "/")
            )
            return [self._store[k] for k in keys]

    def find_subtree(self, name_root):
        prefix = name_root.rstrip("/")
        with self._lock:
            return sorted(
                k for k in self._store if k == prefix or k.startswith(prefix + "/")
            )

    def delete(self, name):
        name = name.rstrip("/")
        with self._lock:
            if name not in self._store:
                raise NameEntryNotFoundError(name)
            del self._store[name]
            self._owned.discard(name)

    def clear_subtree(self, name_root):
        for k in self.find_subtree(name_root):
            with self._lock:
                self._store.pop(k, None)
                self._owned.discard(k)

    def reset(self):
        with self._lock:
            for k in list(self._owned):
                self._store.pop(k, None)
            self._owned.clear()


class NfsNameRecordRepository(NameRecordRepository):
    """One file per key under `record_root`; atomic writes via rename.

    TTL entries are refreshed by a keepalive thread touching mtime; readers
    treat entries with expired TTL as missing.
    """

    TTL_SUFFIX = ".ttl"

    def __init__(self, record_root: str = "/tmp/areal_tpu/name_resolve"):
        self.record_root = Path(record_root)
        self.record_root.mkdir(parents=True, exist_ok=True)
        self._owned: set[str] = set()
        self._keepalive_stop = threading.Event()
        self._keepalive_entries: dict[str, float] = {}
        self._keepalive_thread: threading.Thread | None = None
        self._lock = threading.Lock()

    def _path(self, name: str) -> Path:
        name = name.strip("/")
        return self.record_root / name / "ENTRY"

    def add(self, name, value, delete_on_exit=True, keepalive_ttl=None, replace=False):
        p = self._path(name)
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = p.parent / f".tmp-{uuid.uuid4().hex}"
        tmp.write_text(str(value))
        if replace:
            os.replace(tmp, p)
        else:
            # ATOMIC create-if-absent via link(2) — DistributedLock's mutual
            # exclusion rests on this; an exists()-then-rename check has a
            # window where two hosts both pass and both "acquire".
            try:
                os.link(tmp, p)
            except FileExistsError:
                if self._expired(p):
                    # stale TTL entry: remove and retry the atomic claim
                    # (losers of the link race see FileExistsError again)
                    try:
                        p.unlink()
                    except FileNotFoundError:
                        pass
                    try:
                        os.link(tmp, p)
                    except FileExistsError:
                        tmp.unlink()
                        raise NameEntryExistsError(name) from None
                else:
                    tmp.unlink()
                    raise NameEntryExistsError(name) from None
            tmp.unlink()
        ttl_file = Path(str(p) + self.TTL_SUFFIX)
        if keepalive_ttl is not None:
            ttl_file.write_text(str(float(keepalive_ttl)))
            with self._lock:
                self._keepalive_entries[str(p)] = float(keepalive_ttl)
            self._ensure_keepalive_thread()
        else:
            if ttl_file.exists():
                ttl_file.unlink()
            # The previous incarnation of this entry may have had a TTL; stop
            # refreshing it or the keepalive thread holds it forever.
            with self._lock:
                self._keepalive_entries.pop(str(p), None)
        if delete_on_exit:
            self._owned.add(name)

    def _expired(self, p: Path) -> bool:
        ttl_file = Path(str(p) + self.TTL_SUFFIX)
        if not ttl_file.exists():
            return False
        try:
            ttl = float(ttl_file.read_text())
            return time.time() - p.stat().st_mtime > ttl
        except (OSError, ValueError):
            return False

    def _ensure_keepalive_thread(self):
        if self._keepalive_thread is not None and self._keepalive_thread.is_alive():
            return
        # A previous reset() may have stopped the thread; re-arm the event so
        # entries added after a reset still get keepalive refreshes.
        self._keepalive_stop.clear()

        def _loop():
            while True:
                with self._lock:
                    entries = dict(self._keepalive_entries)
                # Refresh well within the smallest TTL so entries never lapse
                # while their owner is alive.
                interval = min([1.0] + [ttl / 3.0 for ttl in entries.values()])
                if self._keepalive_stop.wait(timeout=max(interval, 0.01)):
                    return
                with self._lock:
                    entries = dict(self._keepalive_entries)
                for path in entries:
                    try:
                        os.utime(path)
                    except OSError:
                        pass

        self._keepalive_thread = threading.Thread(target=_loop, daemon=True)
        self._keepalive_thread.start()

    def get(self, name):
        p = self._path(name)
        if not p.exists() or self._expired(p):
            raise NameEntryNotFoundError(name)
        return p.read_text()

    def find_subtree(self, name_root):
        root = self.record_root / name_root.strip("/")
        if not root.exists():
            return []
        out = []
        for entry in root.rglob("ENTRY"):
            if not self._expired(entry):
                out.append(str(entry.parent.relative_to(self.record_root)))
        return sorted(out)

    def get_subtree(self, name_root):
        out = []
        for k in self.find_subtree(name_root):
            # A peer may delete its entry (or its TTL may lapse) between the
            # listing and the read; skip dead entries instead of crashing.
            try:
                out.append(self.get(k))
            except NameEntryNotFoundError:
                continue
        return out

    def delete(self, name):
        p = self._path(name)
        if not p.exists():
            raise NameEntryNotFoundError(name)
        p.unlink()
        ttl_file = Path(str(p) + self.TTL_SUFFIX)
        if ttl_file.exists():
            ttl_file.unlink()
        with self._lock:
            self._keepalive_entries.pop(str(p), None)
        self._owned.discard(name)

    def clear_subtree(self, name_root):
        root = self.record_root / name_root.strip("/")
        if root.exists():
            shutil.rmtree(root, ignore_errors=True)
        prefix = name_root.strip("/")
        self._owned = {
            n
            for n in self._owned
            if n.strip("/") != prefix and not n.strip("/").startswith(prefix + "/")
        }

    def reset(self):
        # Stop and reap the keepalive thread, then re-arm the event so the
        # repository remains usable (a later add() may need keepalive again).
        self._keepalive_stop.set()
        if self._keepalive_thread is not None:
            self._keepalive_thread.join(timeout=2.0)
            self._keepalive_thread = None
        self._keepalive_stop.clear()
        for name in list(self._owned):
            try:
                self.delete(name)
            except NameEntryNotFoundError:
                pass
        self._owned.clear()


class Etcd3NameRecordRepository(NameRecordRepository):
    """etcd v3 backend over the JSON gRPC-gateway (`/v3/...` HTTP API).

    Parity: areal/utils/name_resolve.py:411 Etcd3NameRecordRepository —
    same contract (TTL leases + keepalive thread, atomic create-if-absent)
    but speaking the gateway's JSON/base64 protocol through stdlib urllib,
    so no etcd3/grpc python packages are required. Works against any etcd
    >= 3.3 with the gateway enabled (the default).
    """

    def __init__(self, addr: str = "localhost:2379", timeout: float = 10.0):
        self.base = f"http://{addr}/v3"
        self.timeout = timeout
        self._owned: set[str] = set()
        self._leases: dict[str, int] = {}  # name -> lease id
        self._lease_ttls: dict[int, float] = {}  # lease id -> granted TTL
        self._keepalive_stop = threading.Event()
        self._keepalive_thread: threading.Thread | None = None
        self._lock = threading.Lock()

    # -- gateway plumbing ----------------------------------------------
    @staticmethod
    def _b64(s: str) -> str:
        import base64

        return base64.b64encode(s.encode()).decode()

    @staticmethod
    def _unb64(s: str) -> str:
        import base64

        return base64.b64decode(s).decode()

    def _call(self, endpoint: str, payload: dict) -> dict:
        import json as _json
        import urllib.request

        req = urllib.request.Request(
            f"{self.base}{endpoint}",
            data=_json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return _json.loads(resp.read().decode() or "{}")

    @staticmethod
    def _range_end(prefix: str) -> str:
        b = bytearray(prefix.encode())
        for i in reversed(range(len(b))):
            if b[i] < 0xFF:
                b[i] += 1
                del b[i + 1 :]
                break
        import base64

        return base64.b64encode(bytes(b)).decode()

    def _key(self, name: str) -> str:
        return "/" + name.strip("/")

    # -- api ------------------------------------------------------------
    def add(self, name, value, delete_on_exit=True, keepalive_ttl=None, replace=False):
        key = self._key(name)
        lease = 0
        if keepalive_ttl is not None:
            out = self._call(
                "/lease/grant", {"TTL": max(1, int(keepalive_ttl))}
            )
            lease = int(out["ID"])
        if replace:
            self._call(
                "/kv/put",
                {"key": self._b64(key), "value": self._b64(str(value)),
                 "lease": lease},
            )
        else:
            # atomic create-if-absent: txn on create_revision == 0
            out = self._call(
                "/kv/txn",
                {
                    "compare": [
                        {
                            "key": self._b64(key),
                            "target": "CREATE",
                            "create_revision": "0",
                            "result": "EQUAL",
                        }
                    ],
                    "success": [
                        {
                            "request_put": {
                                "key": self._b64(key),
                                "value": self._b64(str(value)),
                                "lease": lease,
                            }
                        }
                    ],
                },
            )
            if not out.get("succeeded"):
                if lease:
                    # a failed claim must not leak its freshly granted
                    # lease (contending lockers would accumulate thousands)
                    try:
                        self._call("/lease/revoke", {"ID": lease})
                    except Exception:  # noqa: BLE001 — expires on its own
                        pass
                raise NameEntryExistsError(name)
        with self._lock:
            if lease:
                self._leases[name] = lease
                self._lease_ttls[lease] = float(keepalive_ttl)
                self._ensure_keepalive_thread()
            else:
                self._leases.pop(name, None)
            if delete_on_exit:
                self._owned.add(name)

    def _ensure_keepalive_thread(self):
        if self._keepalive_thread is not None and self._keepalive_thread.is_alive():
            return
        self._keepalive_stop.clear()

        def _loop():
            while True:
                with self._lock:
                    leases = set(self._leases.values())
                    ttls = [self._lease_ttls.get(l, 3.0) for l in leases]
                # refresh well within the smallest TTL (etcd grants >= 1s)
                interval = max(0.2, min(ttls) / 3.0) if ttls else 1.0
                if self._keepalive_stop.wait(timeout=interval):
                    return
                for lease in leases:
                    try:
                        self._call("/lease/keepalive", {"ID": lease})
                    except Exception:  # noqa: BLE001 — retried next tick
                        pass

        self._keepalive_thread = threading.Thread(target=_loop, daemon=True)
        self._keepalive_thread.start()

    def get(self, name):
        out = self._call("/kv/range", {"key": self._b64(self._key(name))})
        kvs = out.get("kvs") or []
        if not kvs:
            raise NameEntryNotFoundError(name)
        return self._unb64(kvs[0]["value"])

    def _range_prefix(self, name_root: str) -> list[tuple[str, str]]:
        prefix = self._key(name_root)
        out = self._call(
            "/kv/range",
            {"key": self._b64(prefix), "range_end": self._range_end(prefix)},
        )
        pairs = []
        for kv in out.get("kvs") or []:
            k = self._unb64(kv["key"])
            # prefix-boundary guard: "/a/b" must not match "/a/bc"
            if k == prefix or k.startswith(prefix + "/"):
                pairs.append((k, self._unb64(kv["value"])))
        return sorted(pairs)

    def get_subtree(self, name_root):
        return [v for _, v in self._range_prefix(name_root)]

    def find_subtree(self, name_root):
        return [k.lstrip("/") for k, _ in self._range_prefix(name_root)]

    def delete(self, name):
        out = self._call(
            "/kv/deleterange", {"key": self._b64(self._key(name))}
        )
        if int(out.get("deleted", 0)) == 0:
            raise NameEntryNotFoundError(name)
        with self._lock:
            self._owned.discard(name)
            self._leases.pop(name, None)

    def clear_subtree(self, name_root):
        prefix = self._key(name_root)
        # two deletes to respect the "/" boundary: the subtree and the root
        self._call(
            "/kv/deleterange",
            {
                "key": self._b64(prefix + "/"),
                "range_end": self._range_end(prefix + "/"),
            },
        )
        self._call("/kv/deleterange", {"key": self._b64(prefix)})
        with self._lock:
            self._owned = {
                n
                for n in self._owned
                if self._key(n) != prefix
                and not self._key(n).startswith(prefix + "/")
            }

    def reset(self):
        self._keepalive_stop.set()
        if self._keepalive_thread is not None:
            self._keepalive_thread.join(timeout=2.0)
            self._keepalive_thread = None
        self._keepalive_stop.clear()
        with self._lock:
            leases = dict(self._leases)
            self._leases.clear()
        for lease in set(leases.values()):
            try:
                self._call("/lease/revoke", {"ID": lease})
            except Exception:  # noqa: BLE001 — lease will expire anyway
                pass
        for name in list(self._owned):
            try:
                self.delete(name)
            except NameEntryNotFoundError:
                pass
        self._owned.clear()


class RayNameRecordRepository(NameRecordRepository):
    """Ray-actor backend (parity: the reference's RayNameResolveRepository,
    areal/utils/name_resolve.py) — a detached named actor holding the dict;
    every method proxies through ray.get. Gated: requires a live ray
    runtime (not in this image; the ray launcher supplies one)."""

    def __init__(self, actor_name: str = "name_resolve"):
        import ray  # gated import — raises cleanly when unavailable

        self._ray = ray

        @ray.remote
        class _Store:
            def __init__(self):
                self.repo = MemoryNameRecordRepository()
                self.expiry: dict[str, float] = {}

            def _expire(self):
                now = time.time()
                for k, dl in list(self.expiry.items()):
                    if dl < now:
                        self.expiry.pop(k, None)
                        try:
                            self.repo.delete(k)
                        except NameEntryNotFoundError:
                            pass

            def call(self, method, *args, **kwargs):
                self._expire()
                ttl = kwargs.pop("_ttl", None)
                out = getattr(self.repo, method)(*args, **kwargs)
                if method == "add" and args:
                    name = args[0].rstrip("/")
                    if ttl is not None:
                        self.expiry[name] = time.time() + ttl
                    else:
                        self.expiry.pop(name, None)
                return out

            def touch(self, names, ttl):
                self._expire()
                for name in names:
                    if name in self.expiry:
                        self.expiry[name] = time.time() + ttl

        # atomic named creation (two workers may bootstrap concurrently)
        self._actor = _Store.options(
            name=actor_name, lifetime="detached", get_if_exists=True
        ).remote()
        self._owned: set[str] = set()
        self._ttl_entries: dict[str, float] = {}
        self._keepalive_stop = threading.Event()
        self._keepalive_thread: threading.Thread | None = None

    def _call(self, method, *args, **kwargs):
        return self._ray.get(self._actor.call.remote(method, *args, **kwargs))

    def _ensure_keepalive(self):
        if self._keepalive_thread is not None and self._keepalive_thread.is_alive():
            return
        self._keepalive_stop.clear()

        def _loop():
            while True:
                entries = dict(self._ttl_entries)
                interval = (
                    max(0.2, min(entries.values()) / 3.0) if entries else 1.0
                )
                if self._keepalive_stop.wait(timeout=interval):
                    return
                by_ttl: dict[float, list[str]] = {}
                for name, ttl in entries.items():
                    by_ttl.setdefault(ttl, []).append(name)
                for ttl, names_ in by_ttl.items():
                    try:
                        self._ray.get(self._actor.touch.remote(names_, ttl))
                    except Exception:  # noqa: BLE001 — retried next tick
                        pass

        self._keepalive_thread = threading.Thread(target=_loop, daemon=True)
        self._keepalive_thread.start()

    def add(self, name, value, delete_on_exit=True, keepalive_ttl=None, replace=False):
        # TTL entries expire actor-side unless this client's keepalive
        # thread refreshes them — crashed owners release their names (the
        # watch_names failure-detection contract the other backends honor).
        self._call(
            "add", name, value, delete_on_exit=False, replace=replace,
            _ttl=keepalive_ttl,
        )
        name_n = name.rstrip("/")
        if keepalive_ttl is not None:
            self._ttl_entries[name_n] = float(keepalive_ttl)
            self._ensure_keepalive()
        else:
            self._ttl_entries.pop(name_n, None)
        if delete_on_exit:
            self._owned.add(name)

    def get(self, name):
        return self._call("get", name)

    def get_subtree(self, name_root):
        return self._call("get_subtree", name_root)

    def find_subtree(self, name_root):
        return self._call("find_subtree", name_root)

    def delete(self, name):
        self._call("delete", name)
        self._owned.discard(name)
        self._ttl_entries.pop(name.rstrip("/"), None)

    def clear_subtree(self, name_root):
        self._call("clear_subtree", name_root)

    def reset(self):
        self._keepalive_stop.set()
        if self._keepalive_thread is not None:
            self._keepalive_thread.join(timeout=2.0)
            self._keepalive_thread = None
        self._keepalive_stop.clear()
        self._ttl_entries.clear()
        for name in list(self._owned):
            try:
                self.delete(name)
            except NameEntryNotFoundError:
                pass
        self._owned.clear()


# Module-level default repository, reconfigurable like the reference.
_default_repo: NameRecordRepository = MemoryNameRecordRepository()


def reconfigure(config: NameResolveConfig) -> None:
    global _default_repo
    if config.type == "memory":
        _default_repo = MemoryNameRecordRepository()
    elif config.type == "nfs":
        _default_repo = NfsNameRecordRepository(config.nfs_record_root)
    elif config.type == "etcd3":
        _default_repo = Etcd3NameRecordRepository(config.etcd3_addr)
    elif config.type == "ray":
        _default_repo = RayNameRecordRepository(config.ray_actor_name)
    else:
        raise NotImplementedError(
            f"name_resolve backend {config.type!r} not available in the TPU build "
            "(supported: memory, nfs, etcd3, ray)"
        )


def to_env(config: NameResolveConfig) -> dict[str, str]:
    """Env vars that ship a NameResolveConfig to subprocesses (decode
    servers, trainer ranks) so every process of an experiment resolves
    names against the SAME store."""
    return {
        "AREAL_NAME_RESOLVE_TYPE": config.type,
        "AREAL_NAME_RESOLVE_NFS_ROOT": config.nfs_record_root,
        "AREAL_NAME_RESOLVE_ETCD_ADDR": config.etcd3_addr,
        "AREAL_NAME_RESOLVE_RAY_ACTOR": config.ray_actor_name,
    }


def reconfigure_from_env() -> bool:
    """Apply AREAL_NAME_RESOLVE_* env (set by launchers); returns whether
    anything was configured."""
    t = os.environ.get("AREAL_NAME_RESOLVE_TYPE")
    if not t:
        return False
    reconfigure(
        NameResolveConfig(
            type=t,
            nfs_record_root=os.environ.get(
                "AREAL_NAME_RESOLVE_NFS_ROOT", "/tmp/areal_tpu/name_resolve"
            ),
            etcd3_addr=os.environ.get(
                "AREAL_NAME_RESOLVE_ETCD_ADDR", "localhost:2379"
            ),
            ray_actor_name=os.environ.get(
                "AREAL_NAME_RESOLVE_RAY_ACTOR", "name_resolve"
            ),
        )
    )
    return True


def default_repo() -> NameRecordRepository:
    return _default_repo


def add(name, value, **kwargs):
    return _default_repo.add(name, value, **kwargs)


def get(name):
    return _default_repo.get(name)


def get_subtree(name_root):
    return _default_repo.get_subtree(name_root)


def find_subtree(name_root):
    return _default_repo.find_subtree(name_root)


def delete(name):
    return _default_repo.delete(name)


def clear_subtree(name_root):
    return _default_repo.clear_subtree(name_root)


def wait(name, timeout=None, poll_frequency=0.1):
    return _default_repo.wait(name, timeout=timeout, poll_frequency=poll_frequency)


def watch_names(names, call_back, poll_frequency=5.0, wait_timeout=300.0):
    return _default_repo.watch_names(names, call_back, poll_frequency, wait_timeout)


def reset():
    return _default_repo.reset()
