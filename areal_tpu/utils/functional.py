"""JAX loss/probability library for PPO/GRPO/SFT.

Parity target: areal/utils/functional.py — gather_logprobs[_entropy] (:43,:84),
masked_normalization (:131), ppo_actor_loss_fn with decoupled behav/proximal
logp (:171), ppo_critic_loss_fn (:247), dynamic_sampling (:314),
reward_overlong_penalty (:376).

TPU-first notes
---------------
- Device functions are pure jax.numpy and jit-safe: no data-dependent Python
  control flow, static shapes, everything fuses into the surrounding step.
- The reference chunks its log-softmax to bound CUDA memory; under XLA the
  [T, V] log-softmax + gather fuses with the logits matmul epilogue, so no
  manual chunking is needed (and would only hurt fusion).
- Under pjit/GSPMD with a fully-specified batch sharding, jnp reductions are
  *global* — the reference's explicit dist.all_reduce disappears into the
  compiler-inserted psum along the mesh's dp axis.
- Host functions (dynamic_sampling, reward shaping) stay numpy: they make
  data-dependent shape decisions, which must happen outside jit.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "gather_logprobs",
    "gather_logprobs_entropy",
    "label_logprobs_of",
    "label_logprobs_entropy_of",
    "clamped_softmax_entropy",
    "clamped_entropy_of",
    "masked_normalization",
    "ppo_actor_loss_fn",
    "ppo_critic_loss_fn",
    "dynamic_sampling",
    "reward_overlong_penalty",
]


def gather_logprobs(
    logits: jax.Array, labels: jax.Array, temperature: float = 1.0
) -> jax.Array:
    """log p(labels) from raw logits; [T, V] + [T] → [T] (float32).

    `temperature` matches the sampling temperature so recomputed logprobs
    align with inference-engine logprobs. Computed in float32 regardless of
    logits dtype — bf16 log-softmax loses ~2 decimal digits which is fatal
    for importance ratios.
    """
    logits = logits.astype(jnp.float32) / max(temperature, 1e-6)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    gathered = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return gathered - logz


def label_logprobs_of(x, labels, temperature: float = 1.0):
    """log p(labels) from either dense [T, V] logits or an LMHead (the
    engine's fused vocab-chunked head, models/qwen2.py::LMHead). Loss
    functions written against this helper work in both engine modes."""
    if hasattr(x, "label_logprobs"):
        return x.label_logprobs(labels, temperature)
    return gather_logprobs(x, labels, temperature)


def label_logprobs_entropy_of(x, labels, temperature: float = 1.0):
    """(log p(labels), entropy) — dense logits or LMHead (see above)."""
    if hasattr(x, "label_logprobs_entropy"):
        return x.label_logprobs_entropy(labels, temperature)
    return gather_logprobs_entropy(x, labels, temperature)


def gather_logprobs_entropy(
    logits: jax.Array, labels: jax.Array, temperature: float = 1.0
) -> tuple[jax.Array, jax.Array]:
    """(log p(labels), entropy) in one pass; shares the logsumexp."""
    logits = logits.astype(jnp.float32) / max(temperature, 1e-6)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    logprobs_all = logits - logz[..., None]
    probs = jnp.exp(logprobs_all)
    entropy = -jnp.sum(probs * logprobs_all, axis=-1)
    gathered = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return gathered - logz, entropy


def clamped_softmax_entropy(
    logits: jax.Array, entropy_clamp: float, temperature: float = 1.0
) -> jax.Array:
    """Token-space-clamped softmax entropy (AEnt regularizer).

    Parity: recipe/AEnt/functional.py:16 (clamped_softmax_entropy) — the
    ``floor(V * entropy_clamp)`` lowest-logit tokens are excluded, the
    remaining distribution renormalized, and its entropy returned. The
    clamp keeps the entropy bonus from pushing probability mass onto the
    garbage tail of the vocabulary.

    TPU-first: the reference round-trips logits to CPU for a bottom-k
    index mask; here the threshold is the k-th order statistic from an
    on-device vocab sort and the entropy comes from a masked logsumexp
    (H = lse - E[x]), all fused by XLA. The keep-mask is stop_gradient'd
    (discrete), the entropy itself is differentiable w.r.t. kept logits.
    Ties at the threshold keep all tied tokens (deterministic, and never
    removes more than the reference would).
    """
    if not 0.0 <= entropy_clamp < 1.0:
        raise ValueError(f"entropy_clamp must be in [0, 1), got {entropy_clamp}")
    v = logits.shape[-1]
    k_rm = min(int(v * entropy_clamp), v - 1)
    x = logits.astype(jnp.float32) / max(temperature, 1e-6)
    if k_rm <= 0:
        logz = jax.scipy.special.logsumexp(x, axis=-1)
        p = jnp.exp(x - logz[..., None])
        return logz - jnp.sum(p * x, axis=-1)
    # smallest KEPT logit: indices [0, k_rm) of the ascending sort are removed
    tau = jax.lax.stop_gradient(jnp.sort(x, axis=-1)[..., k_rm])
    keep = jax.lax.stop_gradient(x >= tau[..., None])
    masked = jnp.where(keep, x, -jnp.inf)
    lse = jax.scipy.special.logsumexp(masked, axis=-1)
    p = jnp.where(keep, jnp.exp(x - lse[..., None]), 0.0)
    return lse - jnp.sum(p * x, axis=-1)


def clamped_entropy_of(x, entropy_clamp: float, temperature: float = 1.0):
    """Clamped entropy — dense [T, V] logits or LMHead (fused vocab head).

    The fused path cannot clamp inside its online-logsumexp vocab scan
    (the threshold is a global order statistic), so LMHead materializes
    logits in token chunks under remat instead (models/qwen2.py::LMHead
    .clamped_entropy)."""
    if hasattr(x, "clamped_entropy"):
        return x.clamped_entropy(entropy_clamp, temperature)
    return clamped_softmax_entropy(x, entropy_clamp, temperature)


def masked_normalization(
    x: jax.Array,
    mask: jax.Array | None = None,
    dim=None,
    unbiased: bool = False,
    eps: float = 1e-5,
    high_precision: bool = True,
) -> jax.Array:
    """Zero-mean unit-var normalization over masked elements (functional.py:131).

    Under pjit the reductions are global across the mesh automatically; no
    explicit all_reduce parameter is needed.
    """
    dtype = jnp.float64 if (high_precision and jax.config.jax_enable_x64) else jnp.float32
    x = x.astype(dtype)
    if dim is None:
        dim = tuple(range(x.ndim))
    if mask is None:
        factor = jnp.asarray(np.prod([x.shape[d] for d in dim]), dtype=dtype)
    else:
        mask = mask.astype(dtype)
        x = x * mask
        factor = mask.sum(axis=dim, keepdims=True)
    x_sum = x.sum(axis=dim, keepdims=True)
    x_sum_sq = (x**2).sum(axis=dim, keepdims=True)
    mean = x_sum / factor
    var = x_sum_sq / factor - mean**2
    var = jnp.where(unbiased, var * factor / jnp.maximum(factor - 1, 1), var)
    return ((x - mean) / (jnp.sqrt(jnp.maximum(var, 0.0)) + eps)).astype(jnp.float32)


def ppo_actor_loss_fn(
    logprobs: jax.Array,
    proximal_logprobs: jax.Array,
    old_logprobs: jax.Array,
    advantages: jax.Array,
    eps_clip: float,
    loss_mask: jax.Array,
    eps_clip_higher: float | None = None,
    c_clip: float | None = None,
    behav_imp_weight_cap: float | None = None,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Clipped-ratio PPO policy loss with the decoupled-PPO split
    (functional.py:171-237; AReaL blog "boba²" decoupled objective).

    Roles of the three logprob streams:
    - `logprobs`           π_θ  — current policy (differentiated)
    - `proximal_logprobs`  π_prox — the proximal policy (recomputed at the
      start of the update); equals old_logprobs in on-policy mode
    - `old_logprobs`       π_behav — the behavior policy that generated the
      tokens (inference engine, possibly stale)

    The clipped ratio is taken against π_prox; a truncated importance weight
    exp(π_prox − π_behav), optionally capped, corrects for staleness.
    """
    loss_mask = loss_mask.astype(bool)
    loss_mask_count = jnp.maximum(loss_mask.sum(), 1)
    ratio = jnp.where(loss_mask, jnp.exp(logprobs - proximal_logprobs), 0.0)

    upper = eps_clip if eps_clip_higher is None else eps_clip_higher
    clipped_ratio = jnp.clip(ratio, 1.0 - eps_clip, 1.0 + upper)

    pg_loss1 = -advantages * ratio
    pg_loss2 = -advantages * clipped_ratio
    clip_mask = pg_loss1 < pg_loss2
    pg_loss = jnp.maximum(pg_loss1, pg_loss2)
    if c_clip is not None:
        assert c_clip > 1.0, c_clip
        pg_loss3 = jnp.sign(advantages) * c_clip * advantages
        dual_clip_mask = pg_loss3 < pg_loss
        pg_loss = jnp.minimum(pg_loss, pg_loss3)
    else:
        dual_clip_mask = jnp.zeros_like(clip_mask)

    behav_kl = proximal_logprobs - old_logprobs
    behav_imp_weight = jnp.exp(behav_kl)
    if behav_imp_weight_cap is not None:
        behav_mask = (behav_imp_weight <= behav_imp_weight_cap) & loss_mask
    else:
        behav_mask = loss_mask
    behav_kl = jnp.where(behav_mask, behav_kl, 0.0)
    behav_imp_weight = jnp.where(behav_mask, behav_imp_weight, 0.0)
    # The behavior importance weight is a correction factor, not a gradient
    # path: stop_gradient matches the reference where it is computed from two
    # non-differentiated streams.
    pg_loss = pg_loss * jax.lax.stop_gradient(behav_imp_weight)

    logging_loss = jax.lax.stop_gradient(pg_loss)
    pg_loss = jnp.where(loss_mask, pg_loss, 0.0).sum() / loss_mask_count
    stat = dict(
        loss=logging_loss,
        importance_weight=jax.lax.stop_gradient(ratio),
        approx_kl=jax.lax.stop_gradient(logprobs - proximal_logprobs),
        clip_mask=clip_mask & loss_mask,
        dual_clip_mask=dual_clip_mask & loss_mask,
        behave_imp_weight=behav_imp_weight,
        behave_approx_kl=behav_kl,
        behave_mask=behav_mask,
    )
    return pg_loss, stat


def _huber_loss(x, y, delta: float = 10.0):
    diff = jnp.abs(x - y)
    return jnp.where(diff < delta, 0.5 * diff**2, delta * (diff - 0.5 * delta))


def _mse_loss(x, y):
    return 0.5 * (x - y) ** 2


def ppo_critic_loss_fn(
    value: jax.Array,
    old_value: jax.Array,
    target_value: jax.Array,
    value_eps_clip: float,
    loss_mask: jax.Array | None = None,
    loss_fn_type: str = "mse",
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Value-clipped critic loss (functional.py:247)."""
    if loss_fn_type == "huber":
        loss_fn = _huber_loss
    elif loss_fn_type == "mse":
        loss_fn = _mse_loss
    else:
        raise NotImplementedError(f"unknown loss fn type {loss_fn_type}")

    loss_orig = loss_fn(value, target_value)
    value_clipped = old_value + jnp.clip(
        value - old_value, -value_eps_clip, value_eps_clip
    )
    loss_clip = loss_fn(value_clipped, target_value)
    value_loss = jnp.maximum(loss_orig, loss_clip)

    clip_mask = jax.lax.stop_gradient(loss_clip > loss_orig)
    if loss_mask is not None:
        loss_mask = loss_mask.astype(bool)
        clip_mask = clip_mask & loss_mask
        value_loss = (
            jnp.where(loss_mask, value_loss, 0.0).sum()
            / jnp.maximum(loss_mask.sum(), 1)
        )
    else:
        value_loss = value_loss.mean()
    stat = dict(clip_mask=clip_mask, loss=jax.lax.stop_gradient(value_loss))
    return value_loss, stat


# ---------------------------------------------------------------------------
# Host-side (data-dependent shapes — must stay out of jit)
# ---------------------------------------------------------------------------


def dynamic_sampling(
    data: dict[str, Any], group_size: int
) -> tuple[dict[str, Any], dict[str, int]]:
    """Drop GRPO groups whose rewards are all equal — they carry zero
    advantage signal (functional.py:314; DAPO). Host-side: changes the batch
    size, so it must run before device dispatch."""
    rewards = np.asarray(data["rewards"])
    batch_size = rewards.shape[0]
    if group_size <= 0:
        return data, dict(n_group_kept=0, n_group_filtered=0)
    if batch_size % group_size != 0:
        return data, dict(n_group_kept=batch_size // group_size, n_group_filtered=0)
    num_groups = batch_size // group_size
    grouped = rewards.reshape(num_groups, group_size)
    all_equal = (grouped == grouped[:, :1]).all(axis=1)
    valid = ~all_equal
    mask = np.repeat(valid, group_size)
    if not mask.any():
        return data, dict(n_group_kept=0, n_group_filtered=num_groups)
    n_kept = int(valid.sum())
    filtered = {}
    for k, v in data.items():
        arr = np.asarray(v) if not isinstance(v, np.ndarray) else v
        if isinstance(v, (np.ndarray, list)) and getattr(arr, "shape", ())[:1] == (batch_size,):
            filtered[k] = arr[mask]
        else:
            filtered[k] = v
    return filtered, dict(n_group_kept=n_kept, n_group_filtered=num_groups - n_kept)


def reward_overlong_penalty(
    data: dict[str, Any],
    overlong_tokens: int,
    overlong_penalty_factor: float,
    max_response_length: int,
) -> dict[str, Any]:
    """DAPO soft overlong penalty: linearly penalise responses that enter the
    last `overlong_tokens` of the budget (functional.py:376). Vectorised."""
    rewards = np.asarray(data["rewards"], dtype=np.float32).copy()
    response_lengths = np.asarray(data["loss_mask"]).sum(axis=-1).astype(np.int64)
    expected_len = max_response_length - overlong_tokens
    exceed = response_lengths - expected_len
    penalty = np.minimum(-exceed / overlong_tokens * overlong_penalty_factor, 0.0)
    data = dict(data)
    data["rewards"] = rewards + penalty.astype(np.float32)
    return data
