"""Chrome-trace (catapult) JSON performance tracer.

Parity target: areal/utils/perf_tracer.py:127 (PerfTracer) — sync/async trace
scopes with categories (compute/comm/io/sync/scheduler), per-rank trace files
merged into one, env-var initialisation, atexit save. Viewable in
chrome://tracing or Perfetto; complements (does not replace) jax.profiler
xprof traces for on-device kernel timing.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from contextlib import contextmanager
from pathlib import Path

_CATEGORIES = ("compute", "comm", "io", "sync", "scheduler", "misc")


class PerfTracer:
    def __init__(self, rank: int = 0, save_path: str | None = None, enabled: bool = True):
        self.rank = rank
        self.save_path = save_path
        self.enabled = enabled
        self._events: list[dict] = []
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        if enabled and save_path:
            atexit.register(self.save)

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    @contextmanager
    def trace_scope(self, name: str, category: str = "compute", **args):
        if not self.enabled:
            yield
            return
        start = self._now_us()
        try:
            yield
        finally:
            end = self._now_us()
            with self._lock:
                self._events.append(
                    dict(
                        name=name,
                        cat=category if category in _CATEGORIES else "misc",
                        ph="X",
                        ts=start,
                        dur=end - start,
                        pid=self.rank,
                        tid=threading.get_ident() % 100000,
                        args=args,
                    )
                )

    # Async (flow) events for cross-thread spans, e.g. a rollout's lifetime.
    def atrace_begin(self, name: str, aid: str, category: str = "scheduler"):
        if not self.enabled:
            return
        with self._lock:
            self._events.append(
                dict(name=name, cat=category, ph="b", id=aid, ts=self._now_us(),
                     pid=self.rank, tid=0)
            )

    def atrace_end(self, name: str, aid: str, category: str = "scheduler"):
        if not self.enabled:
            return
        with self._lock:
            self._events.append(
                dict(name=name, cat=category, ph="e", id=aid, ts=self._now_us(),
                     pid=self.rank, tid=0)
            )

    def instant(self, name: str, category: str = "misc", **args):
        if not self.enabled:
            return
        with self._lock:
            self._events.append(
                dict(name=name, cat=category, ph="i", ts=self._now_us(),
                     pid=self.rank, tid=0, s="p", args=args)
            )

    def save(self, path: str | None = None) -> str | None:
        path = path or self.save_path
        if not path or not self.enabled:
            return None
        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        with self._lock:
            events = list(self._events)
        with open(p, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
        return str(p)

    @staticmethod
    def merge(rank_files: list[str], out_path: str) -> str:
        """Merge per-rank trace files into one (reference merges under flock)."""
        merged: list[dict] = []
        for rf in rank_files:
            try:
                with open(rf) as f:
                    merged.extend(json.load(f).get("traceEvents", []))
            except (OSError, json.JSONDecodeError):
                continue
        with open(out_path, "w") as f:
            json.dump({"traceEvents": merged, "displayTimeUnit": "ms"}, f)
        return out_path


_tracer: PerfTracer | None = None


def init_from_env(rank: int = 0) -> PerfTracer:
    """Initialise the global tracer from AREAL_TPU_PERF_TRACE* env vars."""
    global _tracer
    enabled = os.environ.get("AREAL_TPU_PERF_TRACE", "0") in ("1", "true")
    trace_dir = os.environ.get("AREAL_TPU_PERF_TRACE_DIR", "/tmp/areal_tpu/traces")
    path = os.path.join(trace_dir, f"trace-rank{rank}.json") if enabled else None
    _tracer = PerfTracer(rank=rank, save_path=path, enabled=enabled)
    return _tracer


def get() -> PerfTracer:
    global _tracer
    if _tracer is None:
        _tracer = init_from_env()
    return _tracer


def trace_scope(name: str, category: str = "compute", **args):
    return get().trace_scope(name, category, **args)


# ---------------------------------------------------------------------------
# XLA-level profiling (xprof). The catapult tracer above captures HOST-side
# scheduling; device kernel timelines come from jax.profiler, which writes
# tensorboard/xplane traces (the TPU counterpart of the reference's kineto/
# perfetto CUDA kernel stats, realhf/base/monitor.py:428). Enable per-run
# with AREAL_TPU_XPROF_DIR=/path or scoped via `xprof_trace()`.
# ---------------------------------------------------------------------------


@contextmanager
def xprof_trace(log_dir: str | None = None):
    """Capture a jax.profiler device trace around the enclosed block.

    No-op when no directory is configured (arg or AREAL_TPU_XPROF_DIR) —
    profiling stays opt-in and free when off."""
    import jax

    target = log_dir or os.environ.get("AREAL_TPU_XPROF_DIR")
    if not target:
        yield None
        return
    os.makedirs(target, exist_ok=True)
    jax.profiler.start_trace(target)
    try:
        yield target
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named device-trace region (shows up in xprof timelines); safe and
    ~free when no trace is active."""
    import jax

    return jax.profiler.TraceAnnotation(name)


# jax.profiler supports ONE process-global trace; "owner" records which
# engine claimed the window so co-resident engines (PPO actor + critic
# both call maybe_xprof_step from train_batch) cannot flush or skew each
# other's capture: the first engine to reach the start step owns it.
_xprof_state = {"active": False, "done": False, "owner": None}


def _xprof_flush() -> None:
    if _xprof_state["active"]:
        import jax

        jax.profiler.stop_trace()
        _xprof_state["active"] = False
        _xprof_state["owner"] = None
        _xprof_state["done"] = True


def maybe_xprof_step(step: int, owner: object = None) -> None:
    """Env-gated capture window for training loops: with
    AREAL_TPU_XPROF_DIR set, starts a jax.profiler trace at the first step
    of AREAL_TPU_XPROF_STEPS (default "2-4", inclusive, after warmup
    compiles) and stops it after the last. Called by the train engine at
    the top of every train_batch; free when the env var is unset.

    `owner` identifies the calling engine; the window is claimed by the
    first owner to reach the start step and only that owner's step counter
    advances/ends it."""
    import jax

    target = os.environ.get("AREAL_TPU_XPROF_DIR")
    if not target or _xprof_state["done"]:
        return
    lo, _, hi = os.environ.get("AREAL_TPU_XPROF_STEPS", "2-4").partition("-")
    lo, hi = int(lo), int(hi or lo)
    if not _xprof_state["active"] and lo <= step <= hi:
        os.makedirs(target, exist_ok=True)
        jax.profiler.start_trace(target)
        _xprof_state["active"] = True
        _xprof_state["owner"] = owner
        # short runs (or a crash mid-window) never see a step > hi call;
        # flush at exit so the capture is not silently lost
        atexit.register(_xprof_flush)
    elif (
        _xprof_state["active"]
        and step > hi
        and _xprof_state["owner"] == owner
    ):
        _xprof_flush()
