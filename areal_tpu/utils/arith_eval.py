"""Safe arithmetic expression evaluation via a whitelisted AST walk.

Shared by the countdown reward (integer-only: python's richer literal
syntax — 3_4 digit grouping, floats — would open scoring exploits) and the
TIR calculator tool (floats allowed). No eval(), no names, no calls: the
only accepted nodes are +, -, *, / over numeric literals and parentheses.
"""

from __future__ import annotations

import ast
import re

_ALLOWED_CHARS = re.compile(r"[\d+\-*/().\s]+")


def safe_eval_arithmetic(
    expr: str, allow_float: bool = True
) -> int | float | None:
    """Evaluate `expr`; None on any syntax/operator/value violation.

    The character whitelist runs FIRST: python literal syntax is richer
    than plain arithmetic (e.g. `3_4` parses as the int 34), and for
    reward scoring those forms must be rejected, not normalized."""
    if not _ALLOWED_CHARS.fullmatch(expr):
        return None
    try:
        tree = ast.parse(expr, mode="eval")
    except SyntaxError:
        return None

    # ints stay ints through +,-,* (beyond-2^53 arithmetic must be exact
    # for the calculator tool); only division coerces to float
    def walk(node):
        if isinstance(node, ast.Expression):
            return walk(node.body)
        if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.Add, ast.Sub, ast.Mult, ast.Div)
        ):
            a, b = walk(node.left), walk(node.right)
            if isinstance(node.op, ast.Add):
                return a + b
            if isinstance(node.op, ast.Sub):
                return a - b
            if isinstance(node.op, ast.Mult):
                return a * b
            if b == 0:
                raise ZeroDivisionError
            return a / b
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
            return -walk(node.operand)
        if isinstance(node, ast.Constant):
            if isinstance(node.value, int) and not isinstance(
                node.value, bool
            ):
                return node.value
            if allow_float and isinstance(node.value, float):
                return node.value
        raise ValueError(f"disallowed node {type(node).__name__}")

    try:
        return walk(tree)
    except (ValueError, ZeroDivisionError, RecursionError, OverflowError):
        return None
