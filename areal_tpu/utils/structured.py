"""Structured-config plumbing: nested dataclass ↔ dict conversion and
dotted-path CLI overrides with type coercion.

Replaces the reference's OmegaConf structured merge (areal/api/cli_args.py:
1247-1314) with a dependency-free implementation. Semantics kept:

- YAML files populate nested dataclasses field-by-field; unknown keys raise.
- ``key.subkey=value`` overrides are applied after the file, coerced to the
  annotated type (including Optional[...], lists, bools and enums).
"""

from __future__ import annotations

import dataclasses
import types
import typing
from typing import Any


def is_dataclass_type(tp) -> bool:
    return isinstance(tp, type) and dataclasses.is_dataclass(tp)


def _unwrap_optional(tp):
    """Return (inner_type, is_optional) for Optional[...]/X|None annotations."""
    origin = typing.get_origin(tp)
    if origin is typing.Union or origin is types.UnionType:
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0], True
        return tp, True
    return tp, False


def to_dict(obj: Any) -> Any:
    """Recursively convert dataclasses to plain dicts (YAML-safe)."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {
            f.name: to_dict(getattr(obj, f.name)) for f in dataclasses.fields(obj)
        }
    if isinstance(obj, dict):
        return {k: to_dict(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    return obj


def from_dict(cls, data: dict | None, ignore_unknown: bool = False):
    """Build dataclass `cls` from a nested dict, validating field names.

    `ignore_unknown=True` drops unrecognized keys instead of raising —
    for consumers that read a SUBSET view of a richer config (the local
    launcher parses experiment YAMLs as BaseExperimentConfig while the
    trainer subprocess parses the full subclass)."""
    if data is None:
        return cls()
    if not is_dataclass_type(cls):
        raise TypeError(f"{cls} is not a dataclass")
    field_map = {f.name: f for f in dataclasses.fields(cls)}
    kwargs = {}
    for key, value in data.items():
        if key not in field_map:
            if ignore_unknown:
                continue
            raise ValueError(f"unknown config field {cls.__name__}.{key}")
        f = field_map[key]
        tp, _ = _unwrap_optional(f.type if not isinstance(f.type, str) else _resolve(cls, f.name))
        if is_dataclass_type(tp) and isinstance(value, dict):
            kwargs[key] = from_dict(tp, value, ignore_unknown=ignore_unknown)
        else:
            kwargs[key] = value
    return cls(**kwargs)


def _resolve(cls, field_name: str):
    """Resolve string annotations (from __future__ annotations)."""
    hints = typing.get_type_hints(cls)
    return hints[field_name]


class UnknownFieldError(ValueError):
    """An override names a field the target config class does not have —
    the ONLY override failure a subset-view consumer may ignore (bad
    VALUES for known fields must still fail loudly)."""


def apply_override(obj: Any, dotted_key: str, raw_value: str) -> None:
    """Apply one `a.b.c=value` override in place, coercing to the field type."""
    parts = dotted_key.split(".")
    target = obj
    for part in parts[:-1]:
        if not hasattr(target, part):
            raise UnknownFieldError(f"unknown config field {dotted_key!r}")
        nxt = getattr(target, part)
        if nxt is None:
            # Instantiate Optional nested configs on demand.
            hints = typing.get_type_hints(type(target))
            tp, _ = _unwrap_optional(hints[part])
            if is_dataclass_type(tp):
                nxt = tp()
                setattr(target, part, nxt)
            else:
                raise ValueError(f"cannot descend into None field {part!r}")
        target = nxt
    leaf = parts[-1]
    if not hasattr(target, leaf):
        raise UnknownFieldError(f"unknown config field {dotted_key!r}")
    hints = typing.get_type_hints(type(target))
    tp, optional = _unwrap_optional(hints[leaf])
    setattr(target, leaf, coerce(raw_value, tp, optional))


def coerce(raw: Any, tp, optional: bool = False):
    """Coerce a raw (usually string) CLI value to annotation `tp`."""
    if raw is None:
        return None
    if isinstance(raw, str) and optional and raw.lower() in ("none", "null", "~"):
        return None
    origin = typing.get_origin(tp)
    if origin in (list, tuple):
        inner = (typing.get_args(tp) or (str,))[0]
        if isinstance(raw, str):
            raw = [x for x in raw.strip("[]").split(",") if x != ""]
        seq = [coerce(x.strip() if isinstance(x, str) else x, inner) for x in raw]
        return tuple(seq) if origin is tuple else seq
    if origin is dict:
        if isinstance(raw, dict):
            return raw
        raise ValueError(f"cannot coerce {raw!r} to dict")
    if tp is bool:
        if isinstance(raw, bool):
            return raw
        if raw.lower() in ("1", "true", "yes", "on"):
            return True
        if raw.lower() in ("0", "false", "no", "off"):
            return False
        raise ValueError(f"cannot coerce {raw!r} to bool")
    if tp is int:
        return int(raw)
    if tp is float:
        return float(raw)
    if tp is str or tp is Any:
        return str(raw)
    if is_dataclass_type(tp) and isinstance(raw, dict):
        return from_dict(tp, raw)
    return raw
