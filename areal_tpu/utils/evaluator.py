"""Freq-gated evaluation callback runner.

Parity target: areal/utils/evaluator.py:8 (Evaluator).
"""

from __future__ import annotations

from typing import Callable

from areal_tpu.api.cli_args import EvaluatorConfig
from areal_tpu.api.io_struct import FinetuneSpec
from areal_tpu.utils import logging
from areal_tpu.utils.timeutil import FrequencyControl

logger = logging.getLogger("evaluator")


class Evaluator:
    def __init__(self, config: EvaluatorConfig, ft_spec: FinetuneSpec):
        self.config = config
        self.ft_spec = ft_spec
        self.freq_ctl = FrequencyControl(
            freq_epoch=config.freq_epochs,
            freq_step=config.freq_steps,
            freq_sec=config.freq_secs,
        )

    def evaluate(
        self,
        evaluate_fn: Callable[[], None],
        epoch: int,
        step: int,
        global_step: int,
        force: bool = False,
    ) -> bool:
        """Run `evaluate_fn` if a frequency gate fires; returns whether it ran."""
        if not force and not self.freq_ctl.check(
            epochs=int(step == self.ft_spec.steps_per_epoch - 1), steps=1
        ):
            return False
        logger.info(f"evaluating at global_step {global_step}")
        evaluate_fn()
        return True

    def state_dict(self) -> dict:
        return self.freq_ctl.state_dict()

    def load_state_dict(self, state: dict) -> None:
        self.freq_ctl.load_state_dict(state)
