"""Deterministic per-role seeding (parity: areal/utils/seeding.py).

In JAX, randomness is explicit: we derive a root `jax.random.PRNGKey` from
(seed, key) and hand sub-keys out. We still seed `random`/`numpy` for host-side
shuffling (dataset order, rollout scheduling jitter).
"""

from __future__ import annotations

import hashlib
import random

import numpy as np

_BASE_SEED: int | None = None
_SEED_KEY: str = ""


def _fold(seed: int, key: str) -> int:
    digest = hashlib.sha256(f"{seed}/{key}".encode()).digest()
    return int.from_bytes(digest[:8], "little") % (2**31 - 1)


def set_random_seed(seed: int, key: str) -> None:
    """Seed host-side RNGs deterministically per (seed, role-key) pair."""
    global _BASE_SEED, _SEED_KEY
    _BASE_SEED, _SEED_KEY = seed, key
    folded = _fold(seed, key)
    random.seed(folded)
    np.random.seed(folded % (2**32 - 1))


def get_seed() -> int:
    if _BASE_SEED is None:
        raise RuntimeError("set_random_seed() has not been called")
    return _fold(_BASE_SEED, _SEED_KEY)


def new_prng_key(subkey: str = ""):
    """Derive a jax PRNGKey from the global (seed, key) plus an optional subkey."""
    import jax

    if _BASE_SEED is None:
        raise RuntimeError("set_random_seed() has not been called")
    return jax.random.PRNGKey(_fold(_BASE_SEED, f"{_SEED_KEY}/{subkey}"))
