"""Key layout for the name_resolve discovery service.

Parity: areal/utils/names.py — all keys live under /{experiment}/{trial}.
"""

from __future__ import annotations

ROOT = "areal_tpu"


def _base(experiment_name: str, trial_name: str) -> str:
    return f"{ROOT}/{experiment_name}/{trial_name}"


def gen_servers(experiment_name: str, trial_name: str) -> str:
    return f"{_base(experiment_name, trial_name)}/gen_servers"


def gen_server(experiment_name: str, trial_name: str, server_id: str) -> str:
    return f"{_base(experiment_name, trial_name)}/gen_servers/{server_id}"


def update_weights_from_disk(
    experiment_name: str, trial_name: str, model_version: int
) -> str:
    return f"{_base(experiment_name, trial_name)}/update_weights_from_disk/{model_version}"


def experiment_status(experiment_name: str, trial_name: str) -> str:
    return f"{_base(experiment_name, trial_name)}/experiment_status"


def trainer_rank(experiment_name: str, trial_name: str, rank: int) -> str:
    return f"{_base(experiment_name, trial_name)}/trainer/{rank}"


def distributed_peer(experiment_name: str, trial_name: str, group: str, rank: int) -> str:
    return f"{_base(experiment_name, trial_name)}/peers/{group}/{rank}"


def distributed_barrier(experiment_name: str, trial_name: str, barrier: str) -> str:
    return f"{_base(experiment_name, trial_name)}/barrier/{barrier}"


def model_version(experiment_name: str, trial_name: str, role: str = "default") -> str:
    return f"{_base(experiment_name, trial_name)}/model_version/{role}"


def training_samples(experiment_name: str, trial_name: str) -> str:
    """Trainer-written global consumed-sample counter (the staleness gate's
    numerator; parity: realhf names.training_samples)."""
    return f"{_base(experiment_name, trial_name)}/training_samples"


def rollout_router(experiment_name: str, trial_name: str) -> str:
    """Address of the decode-fleet router service."""
    return f"{_base(experiment_name, trial_name)}/rollout_router"
