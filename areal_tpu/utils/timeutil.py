"""Frequency-gated triggers shared by saver/evaluator/recover.

Parity: areal/utils/timeutil.py (`EpochStepTimeFreqCtl` with independent
epoch/step/time sub-controls and state_dict for recovery). Each sub-gate
tracks its own baseline: a step-triggered fire does NOT reset the seconds
gate, so e.g. freq_step=10 + freq_sec=30 fires on both cadences
independently, matching the reference semantics.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class FrequencyControl:
    """Fires when `freq_epoch` epochs, `freq_step` steps, or `freq_sec`
    seconds have elapsed since that same gate last fired. Any may be None
    (disabled). The three gates are independent.
    """

    freq_epoch: int | None = None
    freq_step: int | None = None
    freq_sec: float | None = None
    initial_value: bool = False

    _last_epoch: int = field(default=0, repr=False)
    _last_step: int = field(default=0, repr=False)
    _last_time: float = field(default_factory=time.monotonic, repr=False)
    _total_epochs: int = field(default=0, repr=False)
    _total_steps: int = field(default=0, repr=False)
    _fired_initial: bool = field(default=False, repr=False)

    def check(self, epochs: int = 0, steps: int = 0) -> bool:
        """Accumulate progress and report whether any gate fires now."""
        self._total_epochs += epochs
        self._total_steps += steps

        if self.initial_value and not self._fired_initial:
            self._fired_initial = True
            self._last_epoch = self._total_epochs
            self._last_step = self._total_steps
            self._last_time = time.monotonic()
            return True

        fire = False
        if (
            self.freq_epoch is not None
            and self._total_epochs - self._last_epoch >= self.freq_epoch
        ):
            fire = True
            self._last_epoch = self._total_epochs
        if (
            self.freq_step is not None
            and self._total_steps - self._last_step >= self.freq_step
        ):
            fire = True
            self._last_step = self._total_steps
        if (
            self.freq_sec is not None
            and time.monotonic() - self._last_time >= self.freq_sec
        ):
            fire = True
            self._last_time = time.monotonic()
        return fire

    def state_dict(self) -> dict:
        return dict(
            last_epoch=self._last_epoch,
            last_step=self._last_step,
            total_epochs=self._total_epochs,
            total_steps=self._total_steps,
            fired_initial=self._fired_initial,
        )

    def load_state_dict(self, state: dict) -> None:
        self._last_epoch = state["last_epoch"]
        self._last_step = state["last_step"]
        self._total_epochs = state["total_epochs"]
        self._total_steps = state["total_steps"]
        self._fired_initial = state.get("fired_initial", False)
        self._last_time = time.monotonic()
