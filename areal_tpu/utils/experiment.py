"""Experiment status broadcast over name_resolve.

Parity: the reference's `ExpStatus` key (realhf/system/master_worker.py:
485-495) — the trainer publishes RUNNING while the loop is alive and a
terminal status on exit, and rollout-side processes (decode servers)
watch the key to self-terminate instead of lingering after the trainer
is gone.
"""

from __future__ import annotations

import enum
import threading

from areal_tpu.utils import logging, name_resolve, names

logger = logging.getLogger("experiment")


class ExpStatus(str, enum.Enum):
    RUNNING = "RUNNING"
    COMPLETE = "COMPLETE"
    ABORTED = "ABORTED"


def publish_status(
    experiment_name: str, trial_name: str, status: ExpStatus | str
) -> None:
    # delete_on_exit=False: a TERMINAL status must outlive the trainer
    # process — watchers read it precisely after the publisher is gone
    name_resolve.add(
        names.experiment_status(experiment_name, trial_name),
        str(getattr(status, "value", status)),
        replace=True,
        delete_on_exit=False,
    )


def get_status(experiment_name: str, trial_name: str) -> ExpStatus | None:
    try:
        raw = name_resolve.get(
            names.experiment_status(experiment_name, trial_name)
        )
    except Exception:  # noqa: BLE001 — absent key/backend: unknown status
        return None
    try:
        return ExpStatus(raw)
    except ValueError:
        return None


def watch_until_terminal(
    experiment_name: str,
    trial_name: str,
    on_terminal,
    poll_interval: float = 5.0,
    stop_event: threading.Event | None = None,
) -> threading.Thread:
    """Background thread: poll the status key; invoke `on_terminal(status)`
    once when it becomes COMPLETE/ABORTED (then exit).

    A missing key is NOT terminal — the trainer may simply not have
    started. And because terminal records deliberately persist across
    runs, a terminal status only counts AFTER this watcher has seen the
    current run's RUNNING: a relaunched fleet must not read the previous
    run's COMPLETE and kill itself at boot."""
    stop_event = stop_event or threading.Event()

    def loop():
        seen_running = False
        while not stop_event.wait(poll_interval):
            status = get_status(experiment_name, trial_name)
            if status == ExpStatus.RUNNING:
                seen_running = True
            elif (
                seen_running
                and status in (ExpStatus.COMPLETE, ExpStatus.ABORTED)
            ):
                logger.info(
                    f"experiment status {status.value}; notifying watcher"
                )
                try:
                    on_terminal(status)
                finally:
                    return

    t = threading.Thread(target=loop, daemon=True, name="exp-status-watch")
    t.stop_event = stop_event  # type: ignore[attr-defined]
    t.start()
    return t


def run_with_status(main_fn, argv) -> None:
    """Example entry-point wrapper: publish RUNNING before `main_fn(argv)`
    and COMPLETE/ABORTED after, on the name_resolve backend the config
    (+ CLI overrides) selects — decode servers watch this key to
    self-terminate with the experiment."""
    from areal_tpu.api.cli_args import NameResolveConfig, parse_cli_args

    cfg_dict, kv = parse_cli_args(argv)
    over = dict(kv)
    expr = (
        over.get("experiment_name") or cfg_dict.get("experiment_name", ""),
        over.get("trial_name") or cfg_dict.get("trial_name", ""),
    )
    if all(expr):
        nr = dict((cfg_dict.get("cluster") or {}).get("name_resolve") or {})
        for k, v in kv:
            if k.startswith("cluster.name_resolve."):
                nr[k.rsplit(".", 1)[1]] = v
        name_resolve.reconfigure(NameResolveConfig(**nr))
    try:
        if all(expr):
            publish_status(*expr, ExpStatus.RUNNING)
        main_fn(argv)
    except BaseException:
        if all(expr):
            publish_status(*expr, ExpStatus.ABORTED)
        raise
    else:
        if all(expr):
            publish_status(*expr, ExpStatus.COMPLETE)
