"""Host networking helpers (parity: areal/utils/network.py)."""

from __future__ import annotations

import socket


def find_free_ports(count: int = 1, low: int = 10000, high: int = 60000) -> list[int]:
    """Find `count` distinct free TCP ports by binding ephemeral sockets."""
    socks, ports = [], []
    try:
        for _ in range(count):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("", 0))
            port = s.getsockname()[1]
            socks.append(s)
            ports.append(port)
    finally:
        for s in socks:
            s.close()
    return ports


def gethostname() -> str:
    return socket.gethostname()


def gethostip() -> str:
    """Best-effort routable IP of this host (no traffic is actually sent)."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return socket.gethostbyname(socket.gethostname())
