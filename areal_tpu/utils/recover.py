"""Step-level fault recovery: checkpoint + restore of the full training state.

Parity target: areal/utils/recover.py:29 (RecoverInfo) and :139
(RecoverHandler). Each dump writes, atomically under a marker file:

  {fileroot}/recover/{experiment}/{trial}/
      recover_info.pkl   — StepInfo + saver/evaluator freq-gate state +
                           dataloader position + engine version
      checkpoint/        — HF-format weights + optimizer state (optim/)

`load` restores engine weights+optimizer, dataloader position, and the
freq-gate states, then the caller re-pushes weights into the inference
servers and resumes from `recover_info.last_step_info.next()` — identical
semantics to the reference's RecoverHandler.
"""

from __future__ import annotations

import os
import pickle
import shutil
from dataclasses import dataclass, field
from typing import Any

from areal_tpu.api.cli_args import RecoverConfig
from areal_tpu.api.io_struct import FinetuneSpec, SaveLoadMeta, StepInfo
from areal_tpu.utils import logging
from areal_tpu.utils.timeutil import FrequencyControl

logger = logging.getLogger("recover")

_DONE_MARKER = "DONE"


@dataclass
class RecoverInfo:
    last_step_info: StepInfo
    saver_info: dict = field(default_factory=dict)
    evaluator_info: dict = field(default_factory=dict)
    dataloader_info: dict = field(default_factory=dict)
    version: int = 0


def recover_root(config: RecoverConfig) -> str:
    return os.path.join(
        config.fileroot, "recover", config.experiment_name, config.trial_name
    )


def check_if_auto_recover(config: RecoverConfig) -> bool:
    """True when mode permits resuming AND a complete recover checkpoint
    exists (reference `check_if_auto_recover`)."""
    if config.mode not in ("auto", "resume", "fault"):
        return False
    root = recover_root(config)
    return os.path.exists(os.path.join(root, _DONE_MARKER)) and os.path.exists(
        os.path.join(root, "recover_info.pkl")
    )


class RecoverHandler:
    def __init__(self, config: RecoverConfig, ft_spec: FinetuneSpec):
        self.config = config
        self.ft_spec = ft_spec
        self.freq_ctl = FrequencyControl(
            freq_epoch=config.freq_epochs,
            freq_step=config.freq_steps,
            freq_sec=config.freq_secs,
        )

    # -- dump -----------------------------------------------------------
    def dump(
        self,
        engine,
        step_info: StepInfo,
        saver=None,
        evaluator=None,
        dataloader=None,
        tokenizer=None,
        force: bool = False,
    ) -> str | None:
        if self.config.mode == "disabled":
            return None
        if not force and not self.freq_ctl.check(
            epochs=int(step_info.epoch_step == step_info.steps_per_epoch - 1),
            steps=1,
        ):
            return None
        root = recover_root(self.config)
        marker = os.path.join(root, _DONE_MARKER)
        if os.path.exists(marker):
            os.remove(marker)
        ckpt = os.path.join(root, "checkpoint")
        os.makedirs(ckpt, exist_ok=True)
        engine.save(
            SaveLoadMeta(
                # orbax: sharded save of params+optimizer, no host gather
                path=ckpt, weight_format="orbax", with_optim=True,
                tokenizer=tokenizer
            )
        )
        info = RecoverInfo(
            last_step_info=step_info,
            saver_info=saver.state_dict() if saver is not None else {},
            evaluator_info=evaluator.state_dict() if evaluator is not None else {},
            dataloader_info=(
                dataloader.state_dict()
                if dataloader is not None and hasattr(dataloader, "state_dict")
                else {}
            ),
            version=engine.get_version(),
        )
        with open(os.path.join(root, "recover_info.pkl"), "wb") as f:
            pickle.dump(info, f)
        with open(marker, "w") as f:
            f.write("ok")
        logger.info(
            f"dumped recover checkpoint at global_step "
            f"{step_info.global_step} -> {root}"
        )
        return root

    # -- load -----------------------------------------------------------
    def load(
        self,
        engine,
        saver=None,
        evaluator=None,
        dataloader=None,
        inference_engine=None,
        weight_update_meta=None,
    ) -> RecoverInfo | None:
        """Restore everything; returns the RecoverInfo (resume from
        `.last_step_info.next()`) or None when no checkpoint exists."""
        if not check_if_auto_recover(self.config):
            return None
        root = recover_root(self.config)
        with open(os.path.join(root, "recover_info.pkl"), "rb") as f:
            info: RecoverInfo = pickle.load(f)
        engine.load(
            SaveLoadMeta(
                path=os.path.join(root, "checkpoint"),
                weight_format="orbax",
                with_optim=True,
            )
        )
        engine.set_version(info.version)
        if saver is not None and info.saver_info:
            saver.load_state_dict(info.saver_info)
        if evaluator is not None and info.evaluator_info:
            evaluator.load_state_dict(info.evaluator_info)
        if dataloader is not None and info.dataloader_info:
            dataloader.load_state_dict(info.dataloader_info)
        if inference_engine is not None:
            inference_engine.set_version(info.version)
            if weight_update_meta is not None:
                # re-push restored weights so decode servers match
                engine.update_weights(weight_update_meta)
        logger.info(
            f"recovered from global_step {info.last_step_info.global_step} "
            f"(version {info.version})"
        )
        return info

    def state_dict(self) -> dict:
        return self.freq_ctl.state_dict()

    def load_state_dict(self, state: dict) -> None:
        self.freq_ctl.load_state_dict(state)


def discard_recover_state(config: RecoverConfig) -> None:
    root = recover_root(config)
    if os.path.exists(root):
        shutil.rmtree(root)
