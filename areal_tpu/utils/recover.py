"""Step-level fault recovery: crash-atomic, versioned checkpoint + restore
of the full training state.

Parity target: areal/utils/recover.py:29 (RecoverInfo) and :139
(RecoverHandler), hardened per ISSUE 14: the trainer is the single
stateful component the whole async loop hangs off, so dying mid-dump must
never destroy the previous recovery point. Layout:

  {fileroot}/recover/{experiment}/{trial}/
      step-{G}/                 — one committed recovery point per dump
          checkpoint/           — orbax sharded params + optimizer
          recover_info.pkl      — StepInfo + freq-gate states + dataloader
                                  position + sample-ledger state + version
          MANIFEST.json         — relpath/size/sha256 of every file above,
                                  fsynced BEFORE the atomic rename commits
                                  the step (no bare pickle trust: the
                                  pickle's checksum is verified before
                                  unpickling)
      step-{G}.tmp/             — an in-progress (or crashed) dump; never
                                  eligible for load
      ledger.wal                — consumed-batch journal (core/sample_ledger)

Dump lifecycle: write everything into `step-{G}.tmp`, fsync the manifest
(and the file payloads it seals), `os.rename` to `step-{G}` (the commit
point), fsync the parent dir, THEN prune to `config.keep_last` committed
steps. A dump failure at any stage degrades to log + metric +
retry-at-the-next-frequency-gate instead of killing the training loop.

`load` walks committed steps newest→oldest, verifying each manifest;
torn / mismatched / half-deleted candidates are skipped (counted in
`recover_torn_skipped_total`) instead of crashing, so a crash mid-dump or
a partially deleted dir costs one recovery point, never the run. The
caller re-pushes weights into the inference servers and resumes from
`recover_info.last_step_info.next()` — identical semantics to the
reference's RecoverHandler.

Fault seams (core/fault_injection): `recover.dump.save` (before the
engine checkpoint), `recover.dump.info` (between checkpoint and
recover_info), `recover.dump.marker` (between manifest and the atomic
rename — the save-vs-marker gap), `recover.load` (per load candidate; an
injected failure skips to the next-older step like any torn candidate).
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import shutil
import threading
from dataclasses import dataclass, field
from typing import Any

from areal_tpu.api.cli_args import RecoverConfig
from areal_tpu.api.io_struct import FinetuneSpec, SaveLoadMeta, StepInfo
from areal_tpu.core import fault_injection
from areal_tpu.utils import logging
from areal_tpu.utils.timeutil import FrequencyControl

logger = logging.getLogger("recover")

_STEP_PREFIX = "step-"
_TMP_SUFFIX = ".tmp"
_MANIFEST = "MANIFEST.json"
_INFO_FILE = "recover_info.pkl"
# name of the sample-ledger write-ahead journal colocated with the steps
LEDGER_WAL = "ledger.wal"


class _RecoverMetrics:
    """Process-wide recovery counters (dump failures are per-process
    evidence, not per-handler: a respawned handler must not zero them)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, int] = {}

    def bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()


_GUARDED_BY = {
    "_RecoverMetrics._counters": "_lock",
}

_METRICS = _RecoverMetrics()


def get_metrics() -> dict[str, int]:
    """Recovery counters: recover_dumps_total, recover_dump_failures_total,
    recover_torn_skipped_total, recover_pruned_total, recover_loads_total."""
    return _METRICS.snapshot()


def reset_metrics() -> None:
    _METRICS.reset()


@dataclass
class RecoverInfo:
    last_step_info: StepInfo
    saver_info: dict = field(default_factory=dict)
    evaluator_info: dict = field(default_factory=dict)
    dataloader_info: dict = field(default_factory=dict)
    # the RecoverHandler's OWN freq-gate state: without it a resumed run's
    # recover gate restarts cold and can re-fire immediately or skip a dump
    recover_ctl_info: dict = field(default_factory=dict)
    # WorkflowExecutor.state_dict(): sample ledger + staleness accounting,
    # journaled with the checkpoint so the staleness cap and exactly-once
    # consumption survive a trainer restart
    ledger_info: dict = field(default_factory=dict)
    version: int = 0


def recover_root(config: RecoverConfig) -> str:
    return os.path.join(
        config.fileroot, "recover", config.experiment_name, config.trial_name
    )


def ledger_wal_path(config: RecoverConfig) -> str:
    """The sample-ledger WAL colocated with (and discarded with) the
    recovery state."""
    return os.path.join(recover_root(config), LEDGER_WAL)


# -- manifest ---------------------------------------------------------------


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # e.g. non-POSIX fs; rename durability is best-effort
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_manifest(step_dir: str, global_step: int) -> None:
    """Seal `step_dir`: record relpath/size/sha256 of every file, fsync the
    payloads and then the manifest itself. Must be the LAST write before
    the atomic rename — a dir whose manifest doesn't verify is torn."""
    files = []
    for dirpath, _dirnames, filenames in os.walk(step_dir):
        for name in sorted(filenames):
            if dirpath == step_dir and name == _MANIFEST:
                continue
            full = os.path.join(dirpath, name)
            rel = os.path.relpath(full, step_dir)
            files.append(
                dict(path=rel, size=os.path.getsize(full), sha256=_sha256(full))
            )
            # the manifest promises these bytes are durable
            with open(full, "rb") as f:
                os.fsync(f.fileno())
    manifest = dict(global_step=global_step, files=sorted(files, key=lambda d: d["path"]))
    mpath = os.path.join(step_dir, _MANIFEST)
    with open(mpath, "w") as f:
        json.dump(manifest, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    _fsync_dir(step_dir)


def verify_step_dir(step_dir: str) -> tuple[bool, str]:
    """Check a committed step dir against its manifest. Returns (ok, reason);
    never raises — an unreadable candidate is just not recoverable."""
    mpath = os.path.join(step_dir, _MANIFEST)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return False, f"manifest unreadable: {e!r}"
    entries = manifest.get("files", [])
    if not any(e["path"] == _INFO_FILE for e in entries):
        return False, "manifest lists no recover_info.pkl"
    for entry in entries:
        full = os.path.join(step_dir, entry["path"])
        if not os.path.exists(full):
            return False, f"missing file {entry['path']}"
        if os.path.getsize(full) != entry["size"]:
            return False, f"size mismatch for {entry['path']}"
        if _sha256(full) != entry["sha256"]:
            return False, f"checksum mismatch for {entry['path']}"
    return True, "ok"


def _step_dirs_newest_first(root: str) -> list[tuple[int, str]]:
    """Committed [(global_step, path)] newest-first; `.tmp` dirs (crashed or
    in-progress dumps) are never candidates."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        if not name.startswith(_STEP_PREFIX) or name.endswith(_TMP_SUFFIX):
            continue
        full = os.path.join(root, name)
        if not os.path.isdir(full):
            continue
        try:
            g = int(name[len(_STEP_PREFIX):])
        except ValueError:
            continue
        out.append((g, full))
    return sorted(out, reverse=True)


def check_if_auto_recover(config: RecoverConfig) -> bool:
    """True when mode permits resuming AND a manifest-verified recovery
    point exists (reference `check_if_auto_recover`, hardened: a
    half-deleted / torn dir is reported as "no recoverable state" instead
    of exploding at `load` time)."""
    if config.mode not in ("auto", "resume", "fault"):
        return False
    candidates = _step_dirs_newest_first(recover_root(config))
    for g, path in candidates:
        ok, reason = verify_step_dir(path)
        if ok:
            return True
        logger.warning(
            f"recover candidate step-{g} fails verification ({reason}); "
            f"checking older checkpoints"
        )
    if candidates:
        logger.warning(
            "no recoverable state: every recover candidate failed "
            "manifest verification"
        )
    return False


class RecoverHandler:
    def __init__(self, config: RecoverConfig, ft_spec: FinetuneSpec):
        self.config = config
        self.ft_spec = ft_spec
        self.freq_ctl = FrequencyControl(
            freq_epoch=config.freq_epochs,
            freq_step=config.freq_steps,
            freq_sec=config.freq_secs,
        )

    # -- dump -----------------------------------------------------------
    def dump(
        self,
        engine,
        step_info: StepInfo,
        saver=None,
        evaluator=None,
        dataloader=None,
        tokenizer=None,
        force: bool = False,
        rollout=None,
    ) -> str | None:
        """Write one crash-atomic recovery point; returns the committed
        `step-{G}` path, or None when the gate didn't fire OR the dump
        failed (failure degrades to log + metric — the training loop keeps
        running and the gate re-fires at its next cadence; the previous
        committed step is untouched either way).

        `rollout` is the inference engine / WorkflowExecutor whose
        `state_dict()` (sample ledger + staleness accounting) is journaled
        with the checkpoint."""
        if self.config.mode == "disabled":
            return None
        if not force and not self.freq_ctl.check(
            epochs=int(step_info.epoch_step == step_info.steps_per_epoch - 1),
            steps=1,
        ):
            return None
        try:
            return self._dump_step(
                engine, step_info, saver, evaluator, dataloader, tokenizer,
                rollout,
            )
        except Exception as e:  # noqa: BLE001 — a failed dump must not kill training
            _METRICS.bump("recover_dump_failures_total")
            logger.error(
                f"recover dump failed at global_step {step_info.global_step}"
                f" ({e!r}); previous recovery points are intact, retrying at"
                f" the next frequency gate"
            )
            return None

    def _dump_step(
        self, engine, step_info, saver, evaluator, dataloader, tokenizer,
        rollout,
    ) -> str:
        root = recover_root(self.config)
        os.makedirs(root, exist_ok=True)
        g = step_info.global_step
        final = os.path.join(root, f"{_STEP_PREFIX}{g}")
        tmp = final + _TMP_SUFFIX
        # a previous crashed attempt at this step leaves a stale tmp dir; a
        # replayed step after recovery leaves a committed step-{G}. Only the
        # tmp is cleared now — the committed dir stays valid until the
        # instant this dump commits (displaced at rename time below).
        for stale in (tmp, final + ".old"):
            if os.path.exists(stale):
                shutil.rmtree(stale)
        ckpt = os.path.join(tmp, "checkpoint")
        os.makedirs(ckpt)
        fault_injection.fire("recover.dump.save", step=g)
        engine.save(
            SaveLoadMeta(
                # orbax: sharded save of params+optimizer, no host gather
                path=ckpt, weight_format="orbax", with_optim=True,
                tokenizer=tokenizer
            )
        )
        fault_injection.fire("recover.dump.info", step=g)
        info = RecoverInfo(
            last_step_info=step_info,
            saver_info=saver.state_dict() if saver is not None else {},
            evaluator_info=evaluator.state_dict() if evaluator is not None else {},
            dataloader_info=(
                dataloader.state_dict()
                if dataloader is not None and hasattr(dataloader, "state_dict")
                else {}
            ),
            recover_ctl_info=self.freq_ctl.state_dict(),
            ledger_info=(
                rollout.state_dict()
                if rollout is not None and hasattr(rollout, "state_dict")
                else {}
            ),
            version=engine.get_version(),
        )
        with open(os.path.join(tmp, _INFO_FILE), "wb") as f:
            pickle.dump(info, f)
            f.flush()
            os.fsync(f.fileno())
        _write_manifest(tmp, g)
        fault_injection.fire("recover.dump.marker", step=g)
        if os.path.exists(final):
            # a replayed step after recovery re-dumps the same G: displace
            # the old dir to a non-candidate name (".old" fails the int()
            # parse) so the unrecoverable window is two renames, not the
            # whole engine.save
            os.rename(final, final + ".old")
            os.rename(tmp, final)  # the commit point
            shutil.rmtree(final + ".old")
        else:
            os.rename(tmp, final)  # the commit point
        _fsync_dir(root)
        _METRICS.bump("recover_dumps_total")
        logger.info(
            f"dumped recover checkpoint at global_step {g} -> {final}"
        )
        self._prune(root)
        return final

    def _prune(self, root: str) -> None:
        keep = max(1, int(self.config.keep_last))
        for g, path in _step_dirs_newest_first(root)[keep:]:
            try:
                shutil.rmtree(path)
                _METRICS.bump("recover_pruned_total")
            except OSError as e:
                # a stuck prune costs disk, not correctness
                logger.warning(f"failed to prune recover step-{g}: {e!r}")

    # -- load -----------------------------------------------------------
    def load(
        self,
        engine,
        saver=None,
        evaluator=None,
        dataloader=None,
        inference_engine=None,
        weight_update_meta=None,
    ) -> RecoverInfo | None:
        """Restore everything from the newest VERIFIED recovery point;
        returns the RecoverInfo (resume from `.last_step_info.next()`) or
        None when no usable checkpoint exists. Torn / mismatched / failing
        candidates are skipped newest→oldest (recover_torn_skipped_total)
        instead of crashing."""
        if self.config.mode not in ("auto", "resume", "fault"):
            return None
        root = recover_root(self.config)
        for g, path in _step_dirs_newest_first(root):
            try:
                fault_injection.fire("recover.load", step=g)
                ok, reason = verify_step_dir(path)
                if not ok:
                    raise RuntimeError(reason)
                # the manifest (verified above) checksummed the pickle —
                # only now is unpickling it trusted
                with open(os.path.join(path, _INFO_FILE), "rb") as f:
                    info: RecoverInfo = pickle.load(f)
                engine.load(
                    SaveLoadMeta(
                        path=os.path.join(path, "checkpoint"),
                        weight_format="orbax",
                        with_optim=True,
                    )
                )
            except Exception as e:  # noqa: BLE001 — walk to the next-older candidate
                _METRICS.bump("recover_torn_skipped_total")
                logger.warning(
                    f"skipping recover candidate step-{g} ({e!r}); "
                    f"falling back to an older checkpoint"
                )
                continue
            engine.set_version(info.version)
            if saver is not None and info.saver_info:
                saver.load_state_dict(info.saver_info)
            if evaluator is not None and info.evaluator_info:
                evaluator.load_state_dict(info.evaluator_info)
            if dataloader is not None and info.dataloader_info:
                dataloader.load_state_dict(info.dataloader_info)
            if info.recover_ctl_info:
                self.freq_ctl.load_state_dict(info.recover_ctl_info)
            if inference_engine is not None:
                inference_engine.set_version(info.version)
                if info.ledger_info and hasattr(
                    inference_engine, "load_state_dict"
                ):
                    inference_engine.load_state_dict(info.ledger_info)
                if weight_update_meta is not None:
                    # re-push restored weights so decode servers match
                    engine.update_weights(weight_update_meta)
            _METRICS.bump("recover_loads_total")
            logger.info(
                f"recovered from global_step {info.last_step_info.global_step}"
                f" (version {info.version}, checkpoint {path})"
            )
            return info
        return None

    def state_dict(self) -> dict:
        return self.freq_ctl.state_dict()

    def load_state_dict(self, state: dict) -> None:
        self.freq_ctl.load_state_dict(state)


def discard_recover_state(config: RecoverConfig) -> None:
    root = recover_root(config)
    if os.path.exists(root):
        shutil.rmtree(root)
