"""ctypes loader for the C++ host kernels (csrc/).

The shared library is compiled on demand with the ambient g++ (one ~1s
compile, cached next to the package in areal_tpu/_native/ and rebuilt when
csrc/datapack.cc is newer). Loading is strictly best-effort: any failure
(no compiler, read-only install, exotic platform) returns None and callers
keep their numpy implementations — native code is an accelerator here,
never a dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from areal_tpu.utils import logging

logger = logging.getLogger("native")

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(os.path.dirname(_PKG_ROOT), "csrc", "datapack.cc")
_SO = os.path.join(_PKG_ROOT, "_native", "libdatapack.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_failed = False


def _build() -> bool:
    if not os.path.exists(_SRC):
        # installed without the csrc/ tree: numpy fallback, no warning
        logger.debug("native datapack source not present; using numpy")
        return False
    cxx = os.environ.get("CXX", "g++")
    cmd = [
        cxx, "-O3", "-fPIC", "-shared", "-std=c++17", "-Wall", _SRC,
        "-o", _SO,
    ]
    try:
        r = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        logger.debug(f"native build unavailable: {e}")
        return False
    if r.returncode != 0:
        logger.warning(
            f"native datapack build failed (falling back to numpy): "
            f"{r.stderr[-500:]}"
        )
        return False
    return True


def load_datapack() -> ctypes.CDLL | None:
    """The datapack shared library, building it if needed; None on any
    failure (callers fall back to the numpy implementations)."""
    global _lib, _failed
    if _lib is not None or _failed:
        return _lib
    with _lock:
        if _lib is not None or _failed:
            return _lib
        try:
            stale = not os.path.exists(_SO) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_SO)
            )
            if stale and not _build():
                _failed = True
                return None
            lib = ctypes.CDLL(_SO)
            lib.ffd_allocate_native.restype = ctypes.c_int64
            lib.ffd_allocate_native.argtypes = [
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int32),
            ]
            lib.partition_balanced_native.restype = ctypes.c_int64
            lib.partition_balanced_native.argtypes = [
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64),
            ]
            _lib = lib
        except Exception as e:  # noqa: BLE001 — never fail the caller
            logger.warning(f"native datapack unavailable: {e}")
            _failed = True
    return _lib
