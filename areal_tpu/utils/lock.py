"""Distributed mutex over the name_resolve store.

Parity: areal/utils/lock.py:9 DistributedLock — the reference mutexes over a
torch TCPStore (counter+owner keys, backoff). The TPU build has no c10d
store; the same semantics come from name_resolve's atomic create-if-absent
(`add(replace=False)` — link(2) on the NFS backend, etcd txn on
create_revision==0), with a keepalive TTL so a crashed holder's lock
self-releases instead of deadlocking the fleet.
"""

from __future__ import annotations

import time
import uuid

from areal_tpu.utils import logging, name_resolve

logger = logging.getLogger("lock")


class DistributedLock:
    def __init__(
        self,
        name: str,
        repo: "name_resolve.NameRecordRepository | None" = None,
        ttl: float = 30.0,
        retry_interval: float = 0.1,
    ):
        self.key = f"locks/{name.strip('/')}"
        self.repo = repo
        self.ttl = ttl
        self.retry_interval = retry_interval
        self.holder_id = uuid.uuid4().hex
        self._held = False

    def _repo(self):
        return self.repo if self.repo is not None else name_resolve.default_repo()

    def acquire(self, timeout: float | None = None) -> bool:
        """Block until acquired (or timeout); returns whether it was."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                self._repo().add(
                    self.key,
                    self.holder_id,
                    delete_on_exit=True,
                    keepalive_ttl=self.ttl,
                    replace=False,
                )
                self._held = True
                return True
            except name_resolve.NameEntryExistsError:
                if deadline is not None and time.monotonic() > deadline:
                    return False
                time.sleep(self.retry_interval)

    def release(self) -> None:
        if not self._held:
            return
        self._held = False
        try:
            # best-effort holder check: never delete someone else's lock
            # (ours may have TTL-lapsed and been re-acquired)
            if self._repo().get(self.key) == self.holder_id:
                self._repo().delete(self.key)
        except name_resolve.NameEntryNotFoundError:
            pass

    def locked(self) -> bool:
        try:
            self._repo().get(self.key)
            return True
        except name_resolve.NameEntryNotFoundError:
            return False

    def __enter__(self) -> "DistributedLock":
        if not self.acquire():
            raise TimeoutError(f"could not acquire lock {self.key}")
        return self

    def __exit__(self, *exc) -> None:
        self.release()
