"""Locks: in-process ranked mutexes + the distributed name_resolve mutex.

OrderedLock — a threading lock with a declared *rank* in a lock hierarchy.
Acquiring a lock whose rank is <= the highest-ranked lock this thread
already holds (in the same domain) raises LockOrderViolation instead of
deadlocking, turning a latent lock-inversion into an immediate, attributed
error. The static half of the contract is areal-lint's AR102/AR103
(areal_tpu/analysis/concurrency.py): the analyzer builds the acquisition-
order graph and checks it against these declared ranks, so inversions are
caught before the interleaving that would trigger them at runtime. The
decode engine's hierarchy (see docs/architecture.md):

    _sched_lock (10)  >  _weight_lock (20)  >  _metrics_lock (30)

(acquire strictly rank-increasing; release in any order).

DistributedLock — parity: areal/utils/lock.py:9 — the reference mutexes
over a torch TCPStore (counter+owner keys, backoff). The TPU build has no
c10d store; the same semantics come from name_resolve's atomic
create-if-absent (`add(replace=False)` — link(2) on the NFS backend, etcd
txn on create_revision==0), with a keepalive TTL so a crashed holder's lock
self-releases instead of deadlocking the fleet. It is NOT reentrant: a
second acquire by the same holder blocks until TTL lapse (see
tests/test_lock.py).
"""

from __future__ import annotations

import threading
import time
import uuid

from areal_tpu.utils import logging, name_resolve

logger = logging.getLogger("lock")


class LockOrderViolation(RuntimeError):
    """Raised when a thread acquires locks against the declared rank order
    (including re-acquiring a non-reentrant OrderedLock it already holds —
    the same bug class, surfaced instead of deadlocking)."""


_held_tls = threading.local()


def _held_stack() -> list:
    stack = getattr(_held_tls, "stack", None)
    if stack is None:
        stack = _held_tls.stack = []
    return stack


class OrderedLock:
    """threading.Lock/RLock with a declared rank in a lock hierarchy.

    Within one `domain`, every thread must acquire OrderedLocks in strictly
    increasing rank. Violations raise LockOrderViolation at acquire time.
    `reentrant=True` uses an RLock and permits re-acquiring the lock at the
    top of this thread's held stack; a non-reentrant re-acquire raises
    (instead of self-deadlocking). Locks in different domains do not
    constrain each other — rank hierarchies are per-subsystem.
    """

    def __init__(
        self,
        name: str,
        rank: int,
        reentrant: bool = False,
        domain: str | None = None,
    ):
        self.name = name
        self.rank = int(rank)
        self.reentrant = reentrant
        # default domain: the dotted prefix ("jax_decode._sched_lock" ->
        # "jax_decode"), so one subsystem's ranks don't constrain another's
        self.domain = domain if domain is not None else name.rsplit(".", 1)[0]
        self._lock = threading.RLock() if reentrant else threading.Lock()

    def _check_order(self) -> None:
        stack = _held_stack()
        if self in stack:
            if self.reentrant:
                return  # re-entry of an already-held RLock is always safe
            raise LockOrderViolation(
                f"re-acquiring non-reentrant lock {self.name!r} "
                "(would self-deadlock)"
            )
        for held in reversed(stack):
            if held.domain != self.domain:
                continue
            if held.rank >= self.rank:
                raise LockOrderViolation(
                    f"acquiring {self.name!r} (rank {self.rank}) while "
                    f"holding {held.name!r} (rank {held.rank}); the "
                    f"{self.domain!r} hierarchy requires strictly "
                    "increasing ranks"
                )
            break  # only the innermost same-domain lock constrains
        return

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._check_order()
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            _held_stack().append(self)
        return ok

    def release(self) -> None:
        stack = _held_stack()
        # remove the most recent occurrence (reentrant locks appear N times)
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break
        self._lock.release()

    def locked(self) -> bool:
        if self.held_by_me():
            return True
        got = self._lock.acquire(blocking=False)
        if got:
            self._lock.release()
            return False
        return True

    def held_by_me(self) -> bool:
        return self in _held_stack()

    def __enter__(self) -> "OrderedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"OrderedLock({self.name!r}, rank={self.rank})"


class DistributedLock:
    def __init__(
        self,
        name: str,
        repo: "name_resolve.NameRecordRepository | None" = None,
        ttl: float = 30.0,
        retry_interval: float = 0.1,
    ):
        self.key = f"locks/{name.strip('/')}"
        self.repo = repo
        self.ttl = ttl
        self.retry_interval = retry_interval
        self.holder_id = uuid.uuid4().hex
        self._held = False

    def _repo(self):
        return self.repo if self.repo is not None else name_resolve.default_repo()

    def acquire(self, timeout: float | None = None) -> bool:
        """Block until acquired (or timeout); returns whether it was."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                self._repo().add(
                    self.key,
                    self.holder_id,
                    delete_on_exit=True,
                    keepalive_ttl=self.ttl,
                    replace=False,
                )
                self._held = True
                return True
            except name_resolve.NameEntryExistsError:
                if deadline is not None and time.monotonic() > deadline:
                    return False
                time.sleep(self.retry_interval)

    def release(self) -> None:
        if not self._held:
            return
        self._held = False
        try:
            # best-effort holder check: never delete someone else's lock
            # (ours may have TTL-lapsed and been re-acquired)
            if self._repo().get(self.key) == self.holder_id:
                self._repo().delete(self.key)
        except name_resolve.NameEntryNotFoundError:
            pass

    def locked(self) -> bool:
        try:
            self._repo().get(self.key)
            return True
        except name_resolve.NameEntryNotFoundError:
            return False

    def __enter__(self) -> "DistributedLock":
        if not self.acquire():
            raise TimeoutError(f"could not acquire lock {self.key}")
        return self

    def __exit__(self, *exc) -> None:
        self.release()
