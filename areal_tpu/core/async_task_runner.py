"""Generic asyncio-in-a-background-thread task executor.

Parity target: areal/core/async_task_runner.py:60 (AsyncTaskRunner) —
submit coroutines from synchronous code, collect completed results,
pause/resume gate, health check, wait(count, timeout).

The trainer thread is synchronous (it drives jit'd device steps); rollout
episodes are coroutines doing HTTP/engine I/O. This runner owns a private
event loop on a daemon thread and bridges the two worlds with thread-safe
queues.
"""

from __future__ import annotations

import asyncio
import queue
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable

from areal_tpu.utils import logging

logger = logging.getLogger("async_task_runner")


class TaskRunnerError(RuntimeError):
    pass


@dataclass
class TaskResult:
    task_id: int
    result: Any = None
    exception: BaseException | None = None
    latency: float = 0.0
    metadata: dict = field(default_factory=dict)


class AsyncTaskRunner:
    """Runs async task factories on a background event loop."""

    def __init__(self, queue_size: int = 1024, name: str = "runner"):
        self.name = name
        self._input: queue.Queue = queue.Queue(maxsize=queue_size)
        self._output: queue.Queue = queue.Queue(maxsize=queue_size)
        self._paused = threading.Event()  # set = paused
        self._shutdown = threading.Event()
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._task_counter = 0
        self._inflight = 0
        self._lock = threading.Lock()
        self._thread_exc: BaseException | None = None

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        started = threading.Event()

        def _run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)
            started.set()
            try:
                self._loop.run_until_complete(self._main())
            except BaseException as e:  # noqa: BLE001
                self._thread_exc = e
                logger.error(f"runner thread died: {e}\n{traceback.format_exc()}")
            finally:
                self._loop.close()

        self._thread = threading.Thread(
            target=_run, daemon=True, name=f"AsyncTaskRunner-{self.name}"
        )
        self._thread.start()
        started.wait()

    async def _main(self):
        pending: set[asyncio.Task] = set()
        while not self._shutdown.is_set():
            # Drain the input queue into asyncio tasks (unless paused).
            while not self._paused.is_set():
                try:
                    task_id, factory, meta = self._input.get_nowait()
                except queue.Empty:
                    break
                task = asyncio.ensure_future(
                    self._execute(task_id, factory, meta)
                )
                pending.add(task)
                task.add_done_callback(pending.discard)
            await asyncio.sleep(0.002)
        if pending:
            for t in pending:
                t.cancel()
            await asyncio.gather(*pending, return_exceptions=True)
        # Close this loop's pooled HTTP session (workflows issue generation
        # requests from this loop) before the loop itself is torn down.
        try:
            from areal_tpu.utils.http import close_current_session

            await close_current_session()
        except Exception as e:  # pragma: no cover - best-effort cleanup
            logger.debug(f"session close on runner shutdown failed: {e!r}")

    async def _execute(self, task_id: int, factory, meta: dict):
        start = time.monotonic()
        finished = False

        def finish(tr: TaskResult) -> None:
            # exactly-once completion accounting: whatever path ends this
            # task (result, failure, cancel, cancel racing a failure), the
            # inflight counter drops ONCE and ONE result is emitted — a
            # leaked decrement here used to wedge StalenessManager capacity
            # (the submitted slot stayed "running" forever)
            nonlocal finished
            if finished:
                return
            finished = True
            with self._lock:
                self._inflight -= 1
            self._output.put(tr)

        try:
            from areal_tpu.core import fault_injection

            fi = fault_injection.get()
            if fi is not None:
                await fi.afire("task.run", task_id=task_id)
            result = await factory()
            finish(
                TaskResult(
                    task_id=task_id,
                    result=result,
                    latency=time.monotonic() - start,
                    metadata=meta,
                )
            )
        except asyncio.CancelledError as e:
            # a cancelled task (pause-window drain, shutdown) still owns a
            # capacity slot — surface a result so the executor releases it
            finish(
                TaskResult(
                    task_id=task_id,
                    exception=e,
                    latency=time.monotonic() - start,
                    metadata=meta,
                )
            )
            raise
        except BaseException as e:  # noqa: BLE001
            logger.error(
                f"task {task_id} failed: {e}\n{traceback.format_exc()}"
            )
            finish(
                TaskResult(
                    task_id=task_id,
                    exception=e,
                    latency=time.monotonic() - start,
                    metadata=meta,
                )
            )

    def destroy(self) -> None:
        self._shutdown.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- health ---------------------------------------------------------
    def health_check(self) -> None:
        if self._thread_exc is not None:
            raise TaskRunnerError(
                f"runner thread crashed: {self._thread_exc}"
            ) from self._thread_exc
        if self._thread is not None and not self._thread.is_alive():
            raise TaskRunnerError("runner thread is not alive")

    # -- flow control ---------------------------------------------------
    def pause(self) -> None:
        """Stop launching queued tasks (in-flight tasks continue)."""
        self._paused.set()

    def resume(self) -> None:
        self._paused.clear()

    @property
    def paused(self) -> bool:
        return self._paused.is_set()

    # -- submission / collection ---------------------------------------
    def submit(
        self, factory: Callable[[], Awaitable[Any]], metadata: dict | None = None
    ) -> int:
        """Enqueue an async task factory; returns its task id."""
        self.health_check()
        with self._lock:
            task_id = self._task_counter
            self._task_counter += 1
            self._inflight += 1
        try:
            self._input.put_nowait((task_id, factory, metadata or {}))
        except queue.Full:
            with self._lock:
                self._inflight -= 1
            raise TaskRunnerError("input queue is full") from None
        return task_id

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def poll_results(self) -> list[TaskResult]:
        """Non-blocking drain of completed results."""
        out = []
        while True:
            try:
                out.append(self._output.get_nowait())
            except queue.Empty:
                return out

    def requeue_results(self, results: list[TaskResult]) -> None:
        """Put drained results back for a later poll. A consumer that
        dies mid-batch (the executor's failure-streak escalation) must
        not drop the unprocessed tail — each result accounts for a
        capacity slot that stays leaked unless someone collects it."""
        for tr in results:
            self._output.put(tr)

    def wait(
        self,
        count: int,
        timeout: float | None = None,
        raise_errors: bool = False,
    ) -> list[TaskResult]:
        """Block until `count` results complete or timeout (TimeoutError)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        results: list[TaskResult] = []
        while len(results) < count:
            self.health_check()
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    # put collected results back? The reference discards
                    # partial waits; we re-queue to avoid losing rollouts.
                    for r in results:
                        self._output.put(r)
                    raise TimeoutError(
                        f"wait({count}) timed out with {len(results)} done"
                    )
            try:
                tr = self._output.get(timeout=min(remaining or 0.1, 0.1))
            except queue.Empty:
                continue
            if tr.exception is not None and raise_errors:
                raise TaskRunnerError(
                    f"task {tr.task_id} failed"
                ) from tr.exception
            results.append(tr)
        return results
