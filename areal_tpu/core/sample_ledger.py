"""Exactly-once sample accounting for the async rollout→train pipeline.

The trainer is the only durable component: when it dies, every accepted
trajectory sitting in `WorkflowExecutor._result_cache` and every rollout
still running on the fleet dies with it (or worse, arrives again after a
restart). The ledger makes trainer death a replayed, verifiable event:

- every submitted episode gets a monotonically increasing **rollout id**;
  accepted trajectories are stamped with (rollout id, weight version);
- `wait()` journals the identities of each consumed training batch into a
  small write-ahead log (`SampleWAL`, JSONL, fsynced per entry) BEFORE the
  batch is trained on — the WAL sequence number is committed inside the
  recover checkpoint, so after a crash the surviving WAL prefix is exactly
  the set of batches whose weight updates are durable;
- on resume, WAL entries past the committed sequence are rolled back
  (their samples are regenerated and re-trained — correct, because the
  weight updates they fed were rolled back with the checkpoint), and a
  trajectory arriving from a still-running fleet replica whose rollout id
  was already consumed is **deduped** at accept time.

Consumed ids travel in the checkpoint (`state_dict`); accepted-but-
unconsumed ids deliberately do not — those trajectories die with the
process, so restoring them would permanently overstate the staleness
cap's `accepted` term. The restored `accepted` count is the consumed
count (see WorkflowExecutor.load_state_dict).

Mutated from the rollout thread (accept/dedup) and the trainer thread
(consume/state_dict), hence the lock.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any

from areal_tpu.utils import logging

logger = logging.getLogger("sample_ledger")


class SampleWAL:
    """Append-only JSONL journal of consumed training batches.

    Each entry: {"seq": int, "version": int, "rids": [int, ...]}. Appends
    are flushed+fsynced so an entry either fully exists or doesn't; a torn
    trailing line (crash mid-append) is dropped at replay/rollback time.
    """

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def append(self, seq: int, version: int, rids: list[int]) -> None:
        entry = dict(seq=seq, version=version, rids=sorted(int(r) for r in rids))
        with open(self.path, "a") as f:
            f.write(json.dumps(entry) + "\n")
            f.flush()
            os.fsync(f.fileno())

    def replay(self) -> list[dict[str, Any]]:
        """All well-formed entries, in file order; a torn trailing line is
        silently dropped (it was never committed)."""
        if not os.path.exists(self.path):
            return []
        entries = []
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                    entries.append(
                        dict(seq=int(e["seq"]), version=int(e["version"]),
                             rids=[int(r) for r in e["rids"]])
                    )
                except (json.JSONDecodeError, KeyError, TypeError, ValueError):
                    logger.warning(
                        f"dropping torn WAL line in {self.path}: {line[:80]!r}"
                    )
        return entries

    def rollback_to(self, committed_seq: int) -> int:
        """Truncate entries with seq > committed_seq (consumed after the
        restored checkpoint committed — their weight updates were rolled
        back, so their samples will be regenerated and re-journaled).
        Returns how many entries were dropped. Atomic: rewrite + rename."""
        entries = self.replay()
        keep = [e for e in entries if e["seq"] <= committed_seq]
        dropped = len(entries) - len(keep)
        if dropped:
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                for e in keep:
                    f.write(json.dumps(e) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.rename(tmp, self.path)
            logger.info(
                f"WAL rollback to seq {committed_seq}: dropped {dropped} "
                f"uncommitted consume entries"
            )
        return dropped


class SampleLedger:
    """Rollout-id issuance + accepted/consumed tracking + dedup."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._next_rid = 0
        # rid -> weight version at accept time; pending consumption
        self._accepted: dict[int, int] = {}
        self._consumed: set[int] = set()
        self._wal_seq = 0
        self._wal: SampleWAL | None = None
        self._deduped_total = 0

    # -- wiring ---------------------------------------------------------
    def attach_wal(self, wal: SampleWAL | None) -> None:
        with self._lock:
            self._wal = wal

    # -- rollout lifecycle ----------------------------------------------
    def new_rid(self) -> int:
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
            return rid

    def on_accepted(self, rid: int, version: int) -> bool:
        """Record an accepted trajectory. False when `rid` was already
        consumed (a duplicate from a still-running replica after resume)
        or is already pending — the caller must treat the trajectory as
        rejected."""
        with self._lock:
            if rid in self._consumed or rid in self._accepted:
                self._deduped_total += 1
                return False
            self._accepted[rid] = version
            # externally-supplied rids must not collide with future issues
            if rid >= self._next_rid:
                self._next_rid = rid + 1
            return True

    def on_consumed(self, rids: list[int], version: int) -> int:
        """Journal one consumed training batch; returns its WAL seq. The
        entry is durable before the caller trains on the batch."""
        with self._lock:
            self._wal_seq += 1
            seq = self._wal_seq
            for rid in rids:
                self._accepted.pop(rid, None)
                self._consumed.add(int(rid))
            wal = self._wal
        if wal is not None:
            wal.append(seq, version, rids)
        return seq

    # -- introspection ---------------------------------------------------
    def consumed_count(self) -> int:
        with self._lock:
            return len(self._consumed)

    def pending_count(self) -> int:
        with self._lock:
            return len(self._accepted)

    def deduped_total(self) -> int:
        with self._lock:
            return self._deduped_total

    def is_consumed(self, rid: int) -> bool:
        with self._lock:
            return rid in self._consumed

    # -- checkpointing ----------------------------------------------------
    def state_dict(self) -> dict[str, Any]:
        """Committed with the recover checkpoint. Pending (accepted but
        unconsumed) entries are intentionally excluded — see module doc."""
        with self._lock:
            return dict(
                next_rid=self._next_rid,
                consumed=sorted(self._consumed),
                wal_seq=self._wal_seq,
            )

    def load_state_dict(self, state: dict[str, Any]) -> None:
        """Restore, then roll the attached WAL back to the committed seq
        so uncommitted consume entries don't survive the restart."""
        with self._lock:
            self._next_rid = int(state.get("next_rid", 0))
            self._consumed = {int(r) for r in state.get("consumed", [])}
            self._accepted = {}
            self._wal_seq = int(state.get("wal_seq", 0))
            wal, seq = self._wal, self._wal_seq
        if wal is not None:
            wal.rollback_to(seq)


_GUARDED_BY = {
    "SampleLedger._next_rid": "_lock",
    "SampleLedger._accepted": "_lock",
    "SampleLedger._consumed": "_lock",
    "SampleLedger._wal_seq": "_lock",
    "SampleLedger._wal": "_lock",
    "SampleLedger._deduped_total": "_lock",
}
