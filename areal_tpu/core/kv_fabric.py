"""Content-addressed KV block keys for the fleet-global KV fabric
(ISSUE 17; ROADMAP item 4 — the fleet-wide generalization of the
SGLang-HiCache single-node tier).

Every complete KV block gets a 64-bit content key:

    key_i = blake2b(key_{i-1} || tokens[i*B:(i+1)*B] || weight_version
                    || kv_dtype)[:8]

The chaining makes keys POSITION-BINDING: key_i equality between two
token sequences implies their entire first (i+1) blocks are identical,
so "this key is resident" means "the whole prefix up to here is
resident" — a matched run never needs per-block token comparison. The
weight_version / kv_dtype salts give the staleness contract for free: a
weight flip or a dtype mismatch changes every key, so stale blocks age
out as honest misses instead of being served.

Keys are blake2b (not Python ``hash``): deterministic across processes
and machines, which is the whole point — a replica's digest must mean
the same thing to the router and to every sibling.

This module is deliberately jax-free (numpy + hashlib only) so the
router and supervisor import it without dragging in the device stack.
"""

from __future__ import annotations

import base64
import hashlib
import logging
import struct
from typing import Iterable, Sequence

import numpy as np

logger = logging.getLogger("areal_tpu.kv_fabric")

# root parent for block 0 of every chain (any fixed 64-bit constant)
CHAIN_ROOT = 0x9E3779B97F4A7C15

# hard cap on digest size (keys) regardless of caller-supplied limits —
# a digest rides inside /metrics JSON and must stay compact
DIGEST_HARD_CAP = 4096


def content_key(
    parent: int,
    token_block: Sequence[int],
    weight_version: int,
    kv_dtype: str,
) -> int:
    """64-bit content key of one block, chained on its parent's key."""
    h = hashlib.blake2b(digest_size=8)
    h.update(struct.pack("<Qq", parent & 0xFFFFFFFFFFFFFFFF, int(weight_version)))
    h.update(kv_dtype.encode())
    h.update(np.asarray(token_block, dtype=np.uint32).tobytes())
    return int.from_bytes(h.digest(), "little")


def chain_keys(
    tokens: Sequence[int],
    block_size: int,
    weight_version: int,
    kv_dtype: str,
    max_blocks: int = 0,
) -> list[int]:
    """Chained content keys for every COMPLETE block of `tokens`.

    The trailing partial block (if any) is never keyed — it is not a
    transferable unit (its pool rows are shared with whatever the owner
    writes next) and the suffix-prefill path recomputes it anyway.
    `max_blocks` > 0 caps the chain length (router-side hint hashing).
    """
    bs = max(1, int(block_size))
    nb = len(tokens) // bs
    if max_blocks > 0:
        nb = min(nb, max_blocks)
    keys: list[int] = []
    parent = CHAIN_ROOT
    for i in range(nb):
        parent = content_key(
            parent, tokens[i * bs : (i + 1) * bs], weight_version, kv_dtype
        )
        keys.append(parent)
    return keys


def longest_run(chain: Sequence[int], resident: "set[int] | dict") -> int:
    """Longest matched prefix run: the largest n such that chain[n-1] is
    resident. Chaining means matching key n-1 implies blocks 0..n-1 all
    match — intermediate membership need not be checked."""
    for n in range(len(chain), 0, -1):
        if chain[n - 1] in resident:
            return n
    return 0


def encode_digest(keys: Iterable[int], cap: int = 1024) -> str:
    """Pack keys into a compact base64 digest (little-endian uint64s).

    Order is caller-meaningful only for hint payloads (a chain run);
    replica digests are just membership sets. Truncates at `cap` keys
    (and at DIGEST_HARD_CAP unconditionally)."""
    cap = min(int(cap), DIGEST_HARD_CAP) if cap > 0 else DIGEST_HARD_CAP
    arr = np.fromiter(
        (int(k) & 0xFFFFFFFFFFFFFFFF for k in keys), dtype=np.uint64
    )[:cap]
    return base64.b64encode(arr.tobytes()).decode("ascii")


def decode_digest(digest: str) -> list[int]:
    """Inverse of encode_digest; malformed input decodes to []."""
    if not digest or not isinstance(digest, str):
        return []
    try:
        raw = base64.b64decode(digest.encode("ascii"), validate=True)
    except Exception as e:  # noqa: BLE001 — a garbled digest is an empty one
        # peers may be mid-upgrade or corrupt; an unreadable digest just
        # means "no resident blocks advertised", never an error path
        logger.debug(f"malformed fabric digest ignored: {e!r}")
        return []
    if len(raw) % 8:
        return []
    return [int(k) for k in np.frombuffer(raw, dtype=np.uint64)]
