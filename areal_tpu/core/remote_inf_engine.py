"""RemoteInfEngine: HTTP client over N decode servers.

Parity target: areal/core/remote_inf_engine.py:192 (RemoteInfEngine) +
:40 (RemoteInfBackendProtocol) + areal/engine/sglang_remote.py (backend
adapter). The client is deliberately backend-agnostic: a `RemoteBackend`
builds/parses the HTTP payloads, so a JetStream or other server can slot in
the way SGLang/vLLM do in the reference.

Key behaviors preserved:
- Server discovery: explicit addrs -> name_resolve subtree ->
  AREAL_LLM_SERVER_ADDRS env (reference :280-307).
- Least-token-load local scheduling (the same estimate the fleet router
  uses: prompt_len + 0.4*max_new_tokens) with rid->server affinity so
  resumed (interrupted) requests land on the server holding their KV
  prefix (reference :404-413); round-robin breaks ties.
- Router-aware failover: a /generate whose transport retries are
  exhausted (replica died mid-request) is re-scheduled — via the fleet
  router with requeue=True, or locally excluding the failed address — and
  re-sent with the SAME delivery id (xid), which the servers' idempotency
  table makes exactly-once (no double-generation, no lost rollout). A 429
  from the router's bounded admission queue is honored by sleeping
  Retry-After and re-asking instead of dogpiling servers directly.
- Interruptible generation loop: when a server flushes a request during a
  weight update the response carries stop_reason="interrupt"; the client
  appends the partial tokens to the prompt and re-submits until finishing
  for a real reason (reference :428-478). Token weight-versions are stamped
  server-side per chunk (stronger than the reference's client-side stamp).
- Weight-update and pause/continue RPCs fan out to every server
  concurrently (reference :767-886; no ProcessPoolExecutor needed — the
  TPU client does no GIL-heavy tensor work).
"""

from __future__ import annotations

import asyncio
import dataclasses
import os
import random
import threading
import time
import uuid
from typing import Any

from areal_tpu.api.cli_args import InferenceEngineConfig
from areal_tpu.api.engine_api import InferenceEngine
from areal_tpu.api.io_struct import ModelRequest, ModelResponse, WeightUpdateMeta
from areal_tpu.core import fault_injection
from areal_tpu.core.workflow_executor import WorkflowExecutor
from areal_tpu.utils import logging, names
from areal_tpu.utils import name_resolve
from areal_tpu.utils.lock import OrderedLock
from areal_tpu.utils.http import (
    HttpRequestError,
    arequest_with_retry,
    close_current_session,
    wait_server_healthy,
)

logger = logging.getLogger("remote_inf_engine")

ROLLOUT_POLL_WAIT_TIME = 0.05


class RemoteBackend:
    """Protocol adapter for one server family (reference
    RemoteInfBackendProtocol, remote_inf_engine.py:40)."""

    PAUSE_ENDPOINT = "/pause_generation"
    CONTINUE_ENDPOINT = "/continue_generation"
    UPDATE_WEIGHTS_FROM_DISK_ENDPOINT = "/update_weights_from_disk"
    SET_VERSION_ENDPOINT = "/set_version"
    HEALTH_ENDPOINT = "/health"

    def build_generate_payload(self, req: ModelRequest) -> dict[str, Any]:
        payload = {
            "rid": req.rid,
            # int() each id: numpy int64s (np.asarray-derived prompts) are
            # not JSON serializable.
            "input_ids": [int(t) for t in req.input_ids],
            "gconfig": dataclasses.asdict(req.gconfig),
        }
        if req.image_data:
            payload["image_data"] = [
                self._encode_image_impl(img) for img in req.image_data
            ]
        return payload

    @staticmethod
    def _encode_image_impl(img: Any) -> str:
        """bytes / base64-str / PIL-style image → base64 string."""
        import base64

        if isinstance(img, (bytes, bytearray)):
            return base64.b64encode(img).decode()
        if isinstance(img, str):
            return img
        if hasattr(img, "save"):  # PIL.Image duck type
            import io

            buf = io.BytesIO()
            img.save(buf, format="PNG")
            return base64.b64encode(buf.getvalue()).decode()
        raise TypeError(
            f"image_data entries must be bytes, base64 str, or PIL images; "
            f"got {type(img).__name__}"
        )

    def parse_generate_response(self, data: dict[str, Any]) -> dict[str, Any]:
        return {
            "output_tokens": [int(t) for t in data["output_tokens"]],
            "output_logprobs": [float(x) for x in data["output_logprobs"]],
            "output_versions": [int(v) for v in data.get("output_versions", [])],
            "stop_reason": data["stop_reason"],
        }


class JaxDecodeBackend(RemoteBackend):
    """Backend speaking areal_tpu/launcher/decode_server.py's protocol."""


class RemoteInfEngine(InferenceEngine):
    def __init__(
        self,
        config: InferenceEngineConfig,
        backend: RemoteBackend | None = None,
        tokenizer: Any = None,
    ):
        self.config = config
        self.backend = backend or JaxDecodeBackend()
        self.tokenizer = tokenizer
        # chaos testing: an enabled FaultInjectionConfig arms the
        # process-global injector (covers every in-process seam — client
        # HTTP, and router/server/engine when co-hosted); disabled, the
        # seams stay single None-checks
        fi_plan = fault_injection.FaultPlan.from_config(
            getattr(config, "fault_injection", None)
        )
        if fi_plan is not None:
            fault_injection.configure(fi_plan)
            logger.warning(
                f"fault injection ARMED: seed={fi_plan.seed} "
                f"{len(fi_plan.points)} point(s) — chaos testing only"
            )
        self.addresses: list[str] = []
        self._router: str | None = None  # cached names.rollout_router lookup
        self._router_next_lookup = 0.0  # negative-lookup cooldown clock
        # round-robin cursor + rid affinity map + per-server estimated
        # token load, all mutated from the rollout event loop AND
        # main-thread callers — one lock for all three
        self._server_idx = 0  # guarded-by: _rid_lock
        self._rid_to_addr: dict[str, str] = {}  # guarded-by: _rid_lock
        # local least-token-load fallback (same estimate the router uses):
        # cost added at choose_server, released when the rid finishes
        self._addr_est_load: dict[str, float] = {}  # guarded-by: _rid_lock
        self._rid_cost: dict[str, float] = {}  # guarded-by: _rid_lock
        self._rid_lock = OrderedLock("remote_inf._rid_lock", rank=10)
        self._version = 0
        self._executor: WorkflowExecutor | None = None
        # weight-sync observability (client side); see get_metrics().
        # stage_weights runs on the trainer's dcn-weight-push daemon thread
        # (DcnWeightPush, engine/jax_engine.py) while commit_staged runs on
        # the main thread — the stats dict needs its own guard (previously
        # unguarded read-modify-write from two threads).
        self._stats_lock = OrderedLock("remote_inf._stats_lock", rank=20)
        self._sync_stats = dict(  # guarded-by: _stats_lock
            n_pushes=0,
            wire_bytes=0,
            # bf16-equivalent bytes had the push shipped fp kernels —
            # wire_bytes_raw / wire_bytes_sent is the int8 weight-serving
            # compression ratio (~2x; see weight_transfer.raw_wire_nbytes)
            wire_bytes_raw=0,
            last_push_bytes=0,
            staging_secs=0.0,
            commit_pause_secs=0.0,
            aborts=0,
        )
        # crash-mid-stage recovery: the push id of a stage_weights whose
        # commit never landed. The NEXT push (the "reconnect") aborts it
        # server-side before staging anything — paired with the servers'
        # push-id-epoch staging reaper (weight_staging_ttl_s).
        self._incomplete_push_id: str | None = None  # guarded-by: _stats_lock

    # -- discovery ------------------------------------------------------
    def _discover_servers(self, addr: str | list[str] | None) -> list[str]:
        if addr:
            return [addr] if isinstance(addr, str) else list(addr)
        if self.config.experiment_name and self.config.trial_name:
            root = names.gen_servers(
                self.config.experiment_name, self.config.trial_name
            )
            deadline = time.monotonic() + self.config.setup_timeout
            while time.monotonic() < deadline:
                found = name_resolve.get_subtree(root)
                if found:
                    return sorted(found)
                time.sleep(1)
        env = os.environ.get("AREAL_LLM_SERVER_ADDRS", "")
        if env:
            return [a.strip() for a in env.split(",") if a.strip()]
        raise RuntimeError(
            "no decode servers found (addr arg, name_resolve, "
            "AREAL_LLM_SERVER_ADDRS all empty)"
        )

    def initialize(
        self,
        addr: str | list[str] | None = None,
        ft_spec: Any = None,
        train_data_parallel_size: int | None = None,
    ) -> "RemoteInfEngine":
        self.addresses = self._discover_servers(addr)

        async def _wait_all():
            try:
                await asyncio.gather(
                    *[
                        wait_server_healthy(a, timeout=self.config.setup_timeout)
                        for a in self.addresses
                    ]
                )
            finally:
                await close_current_session()

        asyncio.run(_wait_all())
        logger.info(f"connected to {len(self.addresses)} decode servers")
        self._executor = WorkflowExecutor(self.config, self)
        self._executor.initialize(train_data_parallel_size)
        return self

    def destroy(self) -> None:
        if self._executor is not None:
            self._executor.destroy()
            self._executor = None

    # -- scheduling -----------------------------------------------------
    def _router_addr(self) -> str | None:
        """Fleet router address, if one registered (names.rollout_router).

        With a router, per-request server choice is delegated to its
        least-load scheduling + qid affinity (parity: GserverManager
        /schedule_request, realhf/system/gserver_manager.py:352); without
        one, the client falls back to local round-robin + rid affinity.
        """
        # positive lookups cache forever; negative ones re-check after a
        # cooldown so a router that registers AFTER the first request still
        # gets picked up (it is launched independently of the trainers)
        if self._router:
            return self._router
        now = time.monotonic()
        if now < self._router_next_lookup:
            return None
        self._router_next_lookup = now + 30.0
        addr = ""
        if self.config.experiment_name and self.config.trial_name:
            try:
                addr = name_resolve.get(
                    names.rollout_router(
                        self.config.experiment_name, self.config.trial_name
                    )
                )
            except Exception as e:  # noqa: BLE001 — router is optional
                logger.debug(f"no rollout router registered ({e!r})")
                addr = ""
        self._router = addr
        return addr or None

    async def _schedule_via_router(
        self,
        req: ModelRequest,
        requeue: bool = False,
        deadline: float | None = None,
    ) -> dict[str, Any] | None:
        """Ask the fleet router for a placement. Returns the router's
        schedule dict — {"url": decode_addr, "prefill_url"?: addr, ...} —
        or None when no router is configured/reachable (local fallback).
        A disaggregated fleet returns BOTH addresses: the client runs
        /prefill on prefill_url (which streams the KV to url server-side)
        and then /generate on url resumes with zero re-prefill."""
        router = self._router_addr()
        if router is None:
            return None
        if deadline is None:
            deadline = time.monotonic() + self.config.request_timeout
        # the prefix the router's affinity hashing buckets (64-token
        # blocks, up to 4): enough for the longest bucket, cheap to ship
        payload = dict(
            qid=req.rid,
            prompt_len=len(req.input_ids),
            group_size=req.gconfig.n_samples,
            new_token_budget=req.gconfig.max_new_tokens,
            input_prefix=[int(t) for t in req.input_ids[:256]],
        )
        if requeue:
            payload["requeue"] = True
        backoff = 1.0
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                # the request's own budget is gone: scheduling it anywhere
                # would only produce work its caller no longer awaits
                logger.warning(
                    f"router schedule for {req.rid} abandoned: deadline "
                    "exhausted"
                )
                return None
            # the router bounds its queue hold by this, so a queued
            # request is shed (not held) once its owner stops caring
            payload["deadline_s"] = remaining
            try:
                out = await arequest_with_retry(
                    router,
                    "/schedule_request",
                    payload=payload,
                    max_retries=2,
                    timeout=min(
                        self.config.router_request_timeout, remaining
                    ),
                )
                return out if out.get("url") else None
            except HttpRequestError as e:
                if e.status == 429 and time.monotonic() < deadline:
                    # the router's bounded admission queue shed us: honor
                    # Retry-After instead of dogpiling a server directly
                    # (which would trigger the preemption storm the queue
                    # exists to prevent). The structured error body carries
                    # retry_after; jitter the wait so a whole shed wave
                    # doesn't come back in lockstep.
                    ra = e.body.get("retry_after")
                    wait = float(ra) if ra is not None else backoff
                    backoff = min(backoff * 2, 10.0)
                    j = max(self.config.retry_jitter, 0.0)
                    wait *= 1.0 + random.uniform(-j, j)
                    await asyncio.sleep(
                        max(0.0, min(wait, deadline - time.monotonic()))
                    )
                    continue
                return self._router_schedule_failed(e)
            except Exception as e:  # noqa: BLE001 — degrade to local policy
                return self._router_schedule_failed(e)

    def _router_schedule_failed(self, e: Exception) -> None:
        logger.warning(f"router schedule failed ({e!r}); using local policy")
        # invalidate the cached address: a restarted router registers
        # under a new port, the cooldown re-lookup will find it
        self._router = ""
        self._router_next_lookup = time.monotonic() + 30.0
        return None

    def choose_server(
        self,
        rid: str | None = None,
        cost: float = 0.0,
        exclude: str | None = None,
    ) -> str:
        """Routerless fallback: pick the server with the least ESTIMATED
        token load (the same prompt + 0.4*budget estimate the fleet
        router's accounting uses — ISSUE 8 satellite: the fallback must
        not bypass the routing policy), round-robin on ties. `cost` is
        charged to the chosen address until `_release_local(rid)`;
        `exclude` skips a failed address during failover."""
        # the whole affinity-lookup + pick sits under _rid_lock: the cursor
        # increment was previously outside it, so concurrent callers
        # (rollout event loop vs main thread) could lose increments and
        # dogpile one server
        with self._rid_lock:
            if rid is not None:
                cached = self._rid_to_addr.get(rid)
                if cached is not None and cached != exclude:
                    return cached
            pool = [a for a in self.addresses if a != exclude] or list(
                self.addresses
            )
            # tie-break by round-robin order so equal-load picks rotate
            n = len(pool)
            order = {
                a: i for i, a in enumerate(
                    pool[self._server_idx % n:] + pool[: self._server_idx % n]
                )
            }
            addr = min(
                pool,
                key=lambda a: (self._addr_est_load.get(a, 0.0), order[a]),
            )
            self._server_idx += 1
            if cost:
                self._addr_est_load[addr] = (
                    self._addr_est_load.get(addr, 0.0) + cost
                )
            if rid is not None:
                self._rid_to_addr[rid] = addr
                if cost:
                    self._rid_cost[rid] = self._rid_cost.get(rid, 0.0) + cost
                if len(self._rid_to_addr) > 65536:
                    # drop oldest half to bound memory (and release their
                    # load estimate — leaked rids must not skew scheduling)
                    for k in list(self._rid_to_addr)[:32768]:
                        self._release_local_locked(k)
        return addr

    def _release_local_locked(self, rid: str) -> None:
        addr = self._rid_to_addr.pop(rid, None)
        c = self._rid_cost.pop(rid, None)
        if addr is not None and c:
            self._addr_est_load[addr] = max(
                0.0, self._addr_est_load.get(addr, 0.0) - c
            )

    def _release_local(self, rid: str) -> None:
        with self._rid_lock:
            self._release_local_locked(rid)

    # -- generation -----------------------------------------------------
    @staticmethod
    def _local_cost(req: ModelRequest) -> float:
        """The router's load estimate, reused by the local fallback."""
        return float(len(req.input_ids)) + 0.4 * float(
            req.gconfig.max_new_tokens
        )

    async def _generate_failover(
        self,
        req: ModelRequest,
        payload: dict[str, Any],
        addr: str,
        deadline: float | None = None,
    ) -> tuple[dict[str, Any], str]:
        """POST /generate with router-aware failover: when the transport
        retries to `addr` are exhausted (the replica died mid-request),
        re-schedule — via the router with requeue=True (whose failover has
        re-pointed the qid at a survivor), or locally excluding the failed
        address — and re-send the SAME payload (same xid: the server-side
        idempotency table makes the retry exactly-once). Every attempt's
        transport timeout is clipped to the request's remaining deadline
        budget, and failover stops once the budget is spent — a request
        never RETRIES past its own deadline. The initial submission always
        ships: a scheduling path that burned the whole budget honoring
        Retry-After degrades to one direct attempt rather than failing
        without ever contacting a server. Returns (response, address that
        served it)."""
        if deadline is None:
            deadline = time.monotonic() + self.config.request_timeout
        for attempt in range(self.config.fleet_failover_retries + 1):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                if attempt == 0:
                    remaining = self.config.request_timeout
                else:
                    raise HttpRequestError(
                        f"/generate for {req.rid} abandoned: request "
                        f"deadline exhausted after {attempt} failover "
                        "attempt(s)"
                    )
            try:
                data = await arequest_with_retry(
                    addr,
                    "/generate",
                    payload=payload,
                    max_retries=self.config.request_retries,
                    timeout=min(self.config.request_timeout, remaining),
                )
                return data, addr
            except Exception as e:  # noqa: BLE001 — classify below
                if (
                    isinstance(e, HttpRequestError)
                    and e.status is not None
                    and e.status < 500
                ):
                    raise  # a real 4xx: retrying elsewhere cannot help
                if attempt >= self.config.fleet_failover_retries:
                    raise
                logger.warning(
                    f"/generate to {addr} failed ({e!r}); failing over"
                )
                sched = await self._schedule_via_router(
                    req, requeue=True, deadline=deadline
                )
                # no prefill handoff on failover: the replacement replica
                # either promotes migrated/parked KV or re-prefills —
                # correctness is identical, only TTFT differs
                routed = sched["url"] if sched else None
                if routed is None or routed == addr:
                    self._release_local(req.rid)
                    routed = self.choose_server(
                        req.rid, cost=self._local_cost(req), exclude=addr
                    )
                if routed == addr:
                    raise  # single-server fleet: nowhere to fail over
                addr = routed
        raise AssertionError("unreachable")

    async def _prefill_handoff(
        self,
        rid: str,
        payload: dict[str, Any],
        prefill_addr: str,
        decode_addr: str,
        deadline: float,
    ) -> bool:
        """Disaggregated handoff: run the prompt on the prefill replica,
        which streams the resulting KV server→server to the decode
        replica (the client never carries KV bytes); the /generate that
        follows resumes it with zero re-prefill. Best-effort by design —
        any failure here degrades to the decode replica prefilling
        itself. One client retry with the SAME xid: the prefill side is
        idempotent and the receiver's staging/commit dedup, so a
        mid-transfer death replays the handoff exactly once."""
        p = dict(payload)
        p["target"] = decode_addr
        p["xid"] = f"pf-{uuid.uuid4().hex}"
        last: Exception | None = None
        for attempt in range(2):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            try:
                out = await arequest_with_retry(
                    prefill_addr,
                    "/prefill",
                    payload=p,
                    max_retries=1,
                    timeout=min(60.0, remaining),
                )
                return bool(out.get("migrated"))
            except Exception as e:  # noqa: BLE001 — degrade to self-prefill
                last = e
        logger.warning(
            f"prefill handoff for {rid} via {prefill_addr} failed "
            f"({last!r}); {decode_addr} will prefill itself"
        )
        return False

    async def agenerate(self, req: ModelRequest) -> ModelResponse:
        """Generate with the interrupt-resume loop (reference :428-478)."""
        start = time.monotonic()
        # the request's whole-lifetime budget: schedule retries, queue
        # wait, 429 sleeps, and failover attempts all draw from it
        deadline = start + self.config.request_timeout
        sched = await self._schedule_via_router(req, deadline=deadline)
        routed = sched["url"] if sched else None
        addr = routed or self.choose_server(
            req.rid, cost=self._local_cost(req)
        )
        # disaggregated fleet: the router named a prefill replica too
        prefill_url = sched.get("prefill_url") if sched else None
        prompt = list(req.input_ids)
        acc_tokens: list[int] = []
        acc_logprobs: list[float] = []
        acc_versions: list[int] = []
        stop_reason = "interrupt"
        ttft = float("inf")
        try:
            while stop_reason == "interrupt":
                work = req.copy()
                work.input_ids = prompt + acc_tokens
                work.gconfig = req.gconfig.new(
                    max_new_tokens=req.gconfig.max_new_tokens - len(acc_tokens),
                    min_new_tokens=max(
                        0, req.gconfig.min_new_tokens - len(acc_tokens)
                    ),
                )
                payload = self.backend.build_generate_payload(work)
                if sched and sched.get("kv_fabric") and not acc_tokens:
                    # fleet KV fabric hint: a sibling holds this prompt's
                    # prefix blocks — the decode server prefetches them
                    # over the migration wire instead of re-prefilling.
                    # First submission only; resumes already have live KV.
                    payload["kv_fabric"] = sched["kv_fabric"]
                if prefill_url and prefill_url != addr:
                    # first submission only: later resume iterations
                    # continue from KV the decode replica already parks
                    await self._prefill_handoff(
                        req.rid, payload, prefill_url, addr, deadline
                    )
                    prefill_url = None
                # delivery id: stable across transport retries AND the
                # failover re-send of THIS submission (so a duplicate can
                # never double-generate), fresh for each resume iteration
                # (which is a new logical submission)
                payload["xid"] = uuid.uuid4().hex
                data, addr = await self._generate_failover(
                    req, payload, addr, deadline=deadline
                )
                out = self.backend.parse_generate_response(data)
                acc_tokens.extend(out["output_tokens"])
                acc_logprobs.extend(out["output_logprobs"])
                versions = out["output_versions"] or [self._version] * len(
                    out["output_tokens"]
                )
                acc_versions.extend(versions)
                if ttft == float("inf") and out["output_tokens"]:
                    ttft = time.monotonic() - start
                stop_reason = out["stop_reason"]
                if stop_reason == "interrupt" and not out["output_tokens"]:
                    # server flushed before producing anything; brief backoff
                    # so the weight swap can finish
                    await asyncio.sleep(ROLLOUT_POLL_WAIT_TIME)
        finally:
            # release bookkeeping even when generation fails — a leaked
            # router qid biases least-load scheduling forever
            self._release_local(req.rid)
            if routed is not None:
                try:
                    # shield: if THIS task is being cancelled (rollout
                    # abort), the release still completes on the loop —
                    # the router's cost unit must not wedge until TTL
                    await asyncio.shield(
                        self._finish_request_best_effort(req.rid)
                    )
                except BaseException as e:  # noqa: BLE001 — release is
                    # best-effort; the router's TTL expiry backstops it
                    logger.debug(f"finish_request({req.rid}) skipped: {e!r}")
        return ModelResponse(
            input_tokens=prompt,
            output_tokens=acc_tokens,
            output_logprobs=acc_logprobs,
            output_versions=acc_versions,
            stop_reason=stop_reason,  # type: ignore[arg-type]
            latency=time.monotonic() - start,
            ttft=ttft,
            tokenizer=self.tokenizer,
        )

    async def _finish_request_best_effort(self, rid: str) -> None:
        """Release one qid's router accounting; failures are logged, never
        raised (the router TTL-expires leaked entries regardless)."""
        try:
            await arequest_with_retry(
                self._router,
                "/finish_request",
                payload=dict(qid=rid),
                max_retries=1,
                timeout=10,
            )
        except Exception as e:  # noqa: BLE001 — accounting is best-effort
            logger.debug(f"finish_request({rid}) failed: {e!r}")

    # -- fanout RPCs ----------------------------------------------------
    def _fanout(
        self,
        endpoint: str,
        payload: dict[str, Any] | None = None,
        timeout: float | None = None,
    ):
        async def _run():
            try:
                return await asyncio.gather(
                    *[
                        arequest_with_retry(
                            a,
                            endpoint,
                            payload=payload,
                            max_retries=self.config.request_retries,
                            timeout=timeout or self.config.setup_timeout,
                        )
                        for a in self.addresses
                    ]
                )
            finally:
                await close_current_session()

        return asyncio.run(_run())

    def pause_generation(self, abort: bool = True):
        self._fanout(self.backend.PAUSE_ENDPOINT, {"abort": abort})

    def continue_generation(self):
        self._fanout(self.backend.CONTINUE_ENDPOINT, {})

    # -- weight updates -------------------------------------------------
    def init_weights_update_group(self, meta: WeightUpdateMeta) -> None:
        pass

    def update_weights_from_disk(self, meta: WeightUpdateMeta) -> None:
        assert meta.path is not None
        self._fanout(
            self.backend.UPDATE_WEIGHTS_FROM_DISK_ENDPOINT,
            {"path": meta.path, "version": self._version},
        )

    @staticmethod
    def _new_push_id() -> str:
        """Unique AND monotonically ordered (ns timestamp prefix, fixed
        width): servers reset staging when a *newer* push id appears and
        reject frames from *older* pushes, so a late retransmitted frame
        from an aborted push can never wipe the current push's staging."""
        import time as _time
        import uuid

        return f"{_time.time_ns():020d}-{uuid.uuid4().hex[:8]}"

    def stage_weights(
        self,
        named: dict[str, Any] | Any,
        push_id: str | None = None,
        chunk_mb: float = 512,
        inflight: int | None = None,
    ) -> str:
        """Stream framed weight buckets into every server's staging area
        with generation LIVE — no pause. The push is pipelined two ways:
        `named` may be a lazy (name, array) producer (the trainer feeds a
        device→host prefetching iterator), and packing runs on a feeder
        thread so building bucket N+1 overlaps the HTTP POST of bucket N,
        with up to `inflight` bucket broadcasts in the air (bounded queue —
        host memory stays at ~inflight × chunk_mb).

        On any failure the server-side staging for this push is dropped via
        /abort_weights before the error propagates, so a crashed push never
        leaks staging memory. Returns the push_id for commit_staged()."""
        import queue as _queue

        from areal_tpu.core.weight_transfer import (
            pack_buckets,
            raw_wire_nbytes,
        )

        if inflight is None:
            inflight = self.config.weight_sync_inflight_buckets
        inflight = max(int(inflight), 1)
        push_id = push_id or self._new_push_id()
        # reconnect recovery: a previous push that staged but never
        # committed (crashed trainer loop, lost commit response) left
        # staging on the servers — drop it explicitly before this push
        # streams, instead of waiting for the newer-id reset to race it
        with self._stats_lock:
            stale_push = self._incomplete_push_id
            self._incomplete_push_id = push_id
        if stale_push is not None and stale_push != push_id:
            logger.warning(
                f"aborting incomplete previous push {stale_push} before "
                f"staging {push_id}"
            )
            self.abort_push(stale_push, forget=False)
        t0 = time.monotonic()
        n_bytes = 0
        raw_bytes = 0  # bf16-equivalent cost, for the compression ratio

        def _count_raw(items):
            nonlocal raw_bytes
            for name, arr in items:
                # metadata-only: .nbytes/.dtype never force a host copy
                raw_bytes += raw_wire_nbytes(
                    name, int(arr.nbytes), str(arr.dtype)
                )
                yield name, arr

        named = _count_raw(
            named.items() if hasattr(named, "items") else named
        )

        # feeder thread: device_get (inside pack's np.ascontiguousarray)
        # + frame packing, decoupled from the event loop by a bounded queue
        q: _queue.Queue = _queue.Queue(maxsize=inflight)
        stop = threading.Event()

        def _put(item) -> bool:
            # stop-aware put: never deadlocks against a dead consumer
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.2)
                    return True
                except _queue.Full:
                    continue
            return False

        def _produce():
            try:
                for b in pack_buckets(named, chunk_mb=chunk_mb):
                    if not _put(b):
                        return
                _put(None)
            except Exception as e:  # noqa: BLE001 — relayed to the consumer
                _put(e)

        feeder = threading.Thread(target=_produce, daemon=True)
        feeder.start()

        async def _drain():
            nonlocal n_bytes
            loop = asyncio.get_running_loop()

            async def _broadcast(b: bytes):
                await fault_injection.afire(
                    "client.weights.stage", push_id=push_id, nbytes=len(b)
                )
                await asyncio.gather(
                    *[
                        arequest_with_retry(
                            a,
                            f"/update_weights_from_tensor?push_id={push_id}",
                            data=b,
                            max_retries=self.config.request_retries,
                            timeout=self.config.request_timeout,
                        )
                        for a in self.addresses
                    ]
                )

            tasks: set[asyncio.Task] = set()
            try:
                while True:
                    item = await loop.run_in_executor(None, q.get)
                    if item is None:
                        break
                    if isinstance(item, Exception):
                        raise item
                    if len(tasks) >= inflight:
                        done, tasks = await asyncio.wait(
                            tasks, return_when=asyncio.FIRST_COMPLETED
                        )
                        for t in done:
                            t.result()  # surface transfer errors
                    n_bytes += len(item) * len(self.addresses)
                    tasks.add(asyncio.create_task(_broadcast(item)))
                if tasks:
                    await asyncio.gather(*tasks)
                    tasks = set()
            finally:
                for t in tasks:
                    t.cancel()
                await close_current_session()

        try:
            asyncio.run(_drain())
        except BaseException:
            stop.set()
            with self._stats_lock:
                self._sync_stats["aborts"] += 1
            self.abort_push(push_id)
            raise
        finally:
            feeder.join(timeout=10)
        with self._stats_lock:
            self._sync_stats["staging_secs"] += time.monotonic() - t0
            self._sync_stats["wire_bytes"] += n_bytes
            self._sync_stats["wire_bytes_raw"] += raw_bytes * len(
                self.addresses
            )
            self._sync_stats["last_push_bytes"] = n_bytes
        return push_id

    def _commit_fanout(
        self,
        push_id: str | None,
        version: int | None,
        lora_scale: float | None,
    ) -> None:
        payload: dict[str, Any] = {"version": version}
        if push_id is not None:
            payload["push_id"] = push_id
        if lora_scale is not None:
            payload["lora_scale"] = float(lora_scale)
        self._fanout(
            "/commit_weights", payload, timeout=self.config.request_timeout
        )
        if version is not None:
            self._version = int(version)
            if self._executor is not None:
                self._executor.set_version(int(version))

    def commit_staged(
        self,
        push_id: str,
        version: int | None = None,
        lora_scale: float | None = None,
    ) -> None:
        """The ONLY pause window of an overlapped push: pause on chunk
        boundaries, commit the staged weights on every server (version
        stamped inside the servers' pause), continue. Observed pause is
        O(device_put apply), not O(network transfer). The commit is
        version-fenced server-side: a stale push_id is rejected, so no
        token can ever mix weight versions."""
        t0 = time.monotonic()
        self.pause_generation(abort=False)
        try:
            self._commit_fanout(push_id, version, lora_scale)
        finally:
            self.continue_generation()
        with self._stats_lock:
            self._sync_stats["commit_pause_secs"] += time.monotonic() - t0
            self._sync_stats["n_pushes"] += 1
            if self._incomplete_push_id == push_id:
                self._incomplete_push_id = None

    def abort_push(self, push_id: str, forget: bool = True) -> None:
        """Drop server-side staging for a failed/abandoned push (explicit
        release — otherwise multi-GiB staging lingers until the next push's
        id happens to reset it). `forget=False` keeps the incomplete-push
        marker owned by the caller (the reconnect path aborts an OLD push
        while a NEW one is already registered)."""
        try:
            self._fanout("/abort_weights", {"push_id": push_id})
        except Exception as e:  # noqa: BLE001 — cleanup is best-effort
            logger.warning(f"abort_weights({push_id}) failed: {e!r}")
        if forget:
            with self._stats_lock:
                if self._incomplete_push_id == push_id:
                    self._incomplete_push_id = None

    def update_weights_from_tensor(
        self,
        named: dict[str, Any] | Any,
        version: int | None = None,
        chunk_mb: float = 512,
        lora_scale: float | None = None,
        overlap: bool | None = None,
        inflight: int | None = None,
    ) -> None:
        """In-memory push: stream framed weight buckets to every server,
        then commit. The TPU analogue of the reference's NCCL broadcast
        fast path (fsdp_engine.py:298-401), with DCN/HTTP as the transport.

        Overlapped mode (default, `weight_sync_overlap`): buckets stage
        with generation LIVE and only /commit_weights runs inside a pause —
        decode servers keep emitting tokens for the whole multi-GiB
        transfer. Legacy mode (overlap=False) pauses for the entire push.
        `lora_scale` marks a LoRA delta push: `named` carries only the
        adapter subtree and servers fold base + scale·A@B at commit."""
        if overlap is None:
            overlap = self.config.weight_sync_overlap
        push_id = self._new_push_id()
        if overlap:
            self.stage_weights(
                named, push_id=push_id, chunk_mb=chunk_mb, inflight=inflight
            )
            self.commit_staged(push_id, version=version, lora_scale=lora_scale)
            return
        t0 = time.monotonic()
        self.pause_generation(abort=False)
        try:
            self.stage_weights(
                named, push_id=push_id, chunk_mb=chunk_mb, inflight=inflight
            )
            self._commit_fanout(push_id, version, lora_scale)
        finally:
            self.continue_generation()
        # legacy mode: the whole push sat inside the pause window
        with self._stats_lock:
            self._sync_stats["commit_pause_secs"] += time.monotonic() - t0
            self._sync_stats["n_pushes"] += 1
            if self._incomplete_push_id == push_id:
                self._incomplete_push_id = None

    def get_metrics(self) -> dict:
        """Client-side weight-sync observability: push counts, wire bytes,
        staging seconds (generation live) vs commit-pause seconds (the only
        window generation actually stops). `wire_bytes_sent` aliases the
        actual bytes; `weight_sync_compression` = raw/sent (1.0 for fp
        pushes, ~2x once the producer quantizes to int8)."""
        with self._stats_lock:
            out = dict(self._sync_stats)
        out["wire_bytes_sent"] = out["wire_bytes"]
        out["weight_sync_compression"] = (
            round(out["wire_bytes_raw"] / out["wire_bytes_sent"], 4)
            if out["wire_bytes_sent"]
            else 1.0
        )
        return out

    def update_weights_from_distributed(self, meta: WeightUpdateMeta, **kw):
        raise NotImplementedError(
            "remote engines receive weights via disk or the DCN transfer "
            "server (update_weights_from_tensor); in-memory jax.Array "
            "handoff is for colocated JaxDecodeEngine"
        )

    def update_weights(self, meta: WeightUpdateMeta) -> None:
        if meta.type == "disk":
            self.update_weights_from_disk(meta)
        else:
            raise NotImplementedError(f"weight update type {meta.type}")

    # -- versioning -----------------------------------------------------
    def set_version(self, version: int) -> None:
        self._version = version
        if self._executor is not None:
            self._executor.set_version(version)
        self._fanout(self.backend.SET_VERSION_ENDPOINT, {"version": version})

    def get_version(self) -> int:
        return self._version

    # -- rollout queue (delegated) -------------------------------------
    def submit(self, data, workflow=None, workflow_builder=None, should_accept=None,
               rollout_id=None):
        return self._executor.submit(
            data, workflow, workflow_builder, should_accept, rollout_id=rollout_id
        )

    def wait(self, count, timeout=None):
        return self._executor.wait(count, timeout=timeout)

    # -- sample-ledger checkpointing (delegated) ------------------------
    def attach_ledger_wal(self, path):
        self._executor.attach_ledger_wal(path)

    def state_dict(self):
        return self._executor.state_dict()

    def load_state_dict(self, state):
        self._executor.load_state_dict(state)

    def rollout_batch(self, data, workflow=None, workflow_builder=None, should_accept=None):
        return self._executor.rollout_batch(
            data, workflow, workflow_builder, should_accept
        )

    def prepare_batch(self, dataloader, workflow=None, workflow_builder=None, should_accept=None):
        return self._executor.prepare_batch(
            dataloader, workflow, workflow_builder, should_accept
        )

    def pause(self):
        self._executor.pause()

    def resume(self):
        self._executor.resume()
