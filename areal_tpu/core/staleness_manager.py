"""Staleness-aware rollout capacity control.

Parity target: areal/core/staleness_manager.py:12. The capacity rule is the
heart of the async-RL data policy (AReaL "boba²"): never admit a rollout
that could be consumed more than `max_staleness` weight-versions after it
was generated:

    staleness_cap = (max_staleness + version + 1) * consumer_batch_size
                    - (accepted + running)
    capacity      = min(max_concurrent - running, staleness_cap)

Counters are mutated from the rollout thread and read from the trainer
thread, hence the lock.
"""

from __future__ import annotations

from threading import Lock

from areal_tpu.api.io_struct import RolloutStat


class StalenessManager:
    def __init__(
        self,
        max_concurrent_rollouts: int,
        consumer_batch_size: int,
        max_staleness: int,
    ):
        self.max_concurrent_rollouts = max_concurrent_rollouts
        self.consumer_batch_size = consumer_batch_size
        self.max_staleness = max_staleness
        self.lock = Lock()
        self.rollout_stat = RolloutStat()

    def get_capacity(self, current_version: int) -> int:
        """Available rollout slots (may be negative when over capacity)."""
        with self.lock:
            concurrency_capacity = (
                max(1, self.max_concurrent_rollouts) - self.rollout_stat.running
            )
            sample_cnt = self.rollout_stat.accepted + self.rollout_stat.running
            staleness_capacity = (
                (self.max_staleness + current_version + 1)
                * max(1, self.consumer_batch_size)
                - sample_cnt
            )
            return min(concurrency_capacity, staleness_capacity)

    def on_rollout_submitted(self) -> None:
        with self.lock:
            self.rollout_stat.submitted += 1
            self.rollout_stat.running += 1

    def on_rollout_accepted(self) -> None:
        with self.lock:
            self.rollout_stat.accepted += 1
            self.rollout_stat.running -= 1

    def on_rollout_rejected(self) -> None:
        with self.lock:
            self.rollout_stat.running -= 1

    def get_stats(self) -> RolloutStat:
        with self.lock:
            return RolloutStat(
                submitted=self.rollout_stat.submitted,
                accepted=self.rollout_stat.accepted,
                running=self.rollout_stat.running,
            )

    # -- checkpointing ---------------------------------------------------
    def state_dict(self) -> dict:
        """Counters as committed with the recover checkpoint. The caller
        (WorkflowExecutor.load_state_dict) overrides `accepted` with the
        ledger's consumed count and forces `running` to 0 on restore —
        in-flight rollouts and cached-but-unconsumed trajectories die with
        the process, so restoring them raw would permanently shrink the
        staleness cap."""
        with self.lock:
            return dict(
                submitted=self.rollout_stat.submitted,
                accepted=self.rollout_stat.accepted,
                running=self.rollout_stat.running,
            )

    def load_state_dict(self, state: dict) -> None:
        with self.lock:
            self.rollout_stat.submitted = int(state.get("submitted", 0))
            self.rollout_stat.accepted = int(state.get("accepted", 0))
            self.rollout_stat.running = int(state.get("running", 0))
