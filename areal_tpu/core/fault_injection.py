"""Deterministic fault injection for the async fleet (ISSUE 9 tentpole).

The fully-asynchronous stack (client -> router -> decode servers, weight
push, host-KV tier, rollout executor) is only trustworthy if it DEGRADES
instead of corrupting data when a component fails (Podracer's anti-fragile
actor fleets; LlamaRL treats worker loss as routine). This module gives
every cross-component boundary a named injection seam and a seed-driven
plan that perturbs those seams reproducibly, so `bench.py --mode chaos`
and `tests/test_chaos.py` can replay a whole fleet trace under a fault
schedule and assert the exactly-once / bit-identical-stream invariants.

Seams (grep for `fault_injection.fire(` / `.afire(` / `.tear(`):

  client.http.send      utils/http.py        before the request leaves —
                                             an abort here means the server
                                             never saw it (no effect)
  client.http.recv      utils/http.py        after a 2xx response arrived —
                                             an abort here is the
                                             ERROR-AFTER-EFFECT shape: the
                                             side effect landed, the
                                             response is lost, and only
                                             idempotency saves the retry
  client.http.body      utils/http.py        torn/truncated response body
  client.weights.stage  core/remote_inf_engine.py  per staged bucket
  router.schedule       launcher/router.py   /schedule_request handling
  router.poll           launcher/router.py   per-replica health/metrics probe
  supervisor.spawn      launcher/supervisor.py  before each spawn attempt
                                             (abort = launcher failure —
                                             jittered-backoff retry, then
                                             crash-loop escalation at
                                             spawn_max_attempts)
  supervisor.drain      launcher/supervisor.py  inside the drain deadline
                                             window (delay = a HUNG drain:
                                             the deadline aborts the
                                             action and rolls it back)
  supervisor.health     launcher/supervisor.py  before each replica health
                                             probe (abort = health flap;
                                             sustained aborts look like
                                             death and trigger replace)
  supervisor.kill       launcher/supervisor.py  after a drain commits,
                                             before the kill (abort = the
                                             supervisor dying mid
                                             transition — the next tick
                                             replans; the /drain
                                             in-progress guard makes the
                                             retried drain safe)
  server.generate       launcher/decode_server.py  before the engine runs
  server.prefill        launcher/decode_server.py  before a prefill-only
                                             admission (disaggregated role)
  server.weights.stage  launcher/decode_server.py  per received bucket
  server.weights.commit launcher/decode_server.py  before the install
  weight.stage.add      core/weight_transfer.py    WeightStaging.add_bucket
                                             (fires for KV-session frames
                                             too — they ride the same
                                             staging)
  kv.swap_out           engine/kv_pool.py    HostKVStore.put (D2H offload;
                                             also migration imports)
  kv.swap_in            engine/kv_pool.py    HostKVStore.take (promotion)
  kv.migrate.send       launcher/decode_server.py  per outbound KV-session
                                             frame (handoff/drain sender);
                                             an abort is the sender dying
                                             mid-stream — the same-xid
                                             full replay must land the
                                             session exactly once
  kv.migrate.recv       launcher/decode_server.py  per inbound KV frame;
                                             torn honored here (manifest
                                             length-check rejects before
                                             a byte stages)
  task.run              core/async_task_runner.py  rollout task execution
  recover.dump.save     utils/recover.py     before the engine checkpoint
                                             is written into step-{G}.tmp
                                             (abort = trainer dying
                                             mid-save; the tmp dir is never
                                             a load candidate)
  recover.dump.info     utils/recover.py     between the engine checkpoint
                                             and recover_info.pkl (a
                                             weights-without-metadata tear)
  recover.dump.marker   utils/recover.py     between the fsynced manifest
                                             and the atomic rename — the
                                             save-vs-marker gap: everything
                                             written, nothing committed
  recover.load          utils/recover.py     per load candidate (abort =
                                             a torn/unreadable checkpoint;
                                             the walk falls back to the
                                             next-older committed step)
  train.step            engine/jax_engine.py before each optimizer step
                                             (trainer death with weights
                                             half-applied in HBM only)
  train.weights.push    engine/jax_engine.py TrainEngine.update_weights
                                             entry — trainer death mid
                                             weight-push; decode keeps the
                                             old version until the restored
                                             trainer re-pushes
  dataloader.next       dataset/__init__.py  before each batch is yielded
                                             (death in the fetch-to-consume
                                             window; the restored position
                                             re-yields the batch)

Fault modes:

  abort               raise InjectedFault (at a pre-effect seam: clean loss)
  error_after_effect  raise InjectedFault at a post-effect seam — the
                      response is lost but the side effect landed; the mode
                      name documents intent, the mechanics equal `abort`
  delay               fixed + seed-jittered sleep (a SLOW replica, not a
                      dead one — what circuit breakers exist to catch)
  torn                truncate a payload at a seeded fraction; only the
                      `tear()` entry point honors torn points (fire/afire
                      skip them without consuming a hit, so a seam that
                      calls BOTH fire and tear — weight.stage.add — keeps
                      abort and torn points independent)

Determinism: every random draw (probability gates, jitter, tear fraction)
comes from a per-point `random.Random(seed + index)` stream, and per-point
hit counters are serialized under one lock — a plan replays the same
decisions for the same sequence of seam visits. The invariant chaos proofs
actually rely on is stronger and simpler: the ACCEPTED token streams are a
pure function of the request set, never of the fault schedule.

The injector is process-global (`configure` / `deactivate`); when inactive
every seam is a single `is None` check, so production paths pay nothing.
"""

from __future__ import annotations

import asyncio
import fnmatch
import json
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any

from areal_tpu.utils import logging

logger = logging.getLogger("fault_injection")


class InjectedFault(RuntimeError):
    """A fault raised by the injector at a registered seam."""

    def __init__(self, site: str, mode: str, point: "FaultPoint"):
        super().__init__(f"injected {mode} at {site} (point {point.site!r})")
        self.site = site
        self.mode = mode
        self.point = point


_MODES = ("abort", "error_after_effect", "delay", "torn")


@dataclass
class FaultPoint:
    """One entry of a fault plan.

    site:     fnmatch pattern over seam names ("client.http.*").
    mode:     one of abort / error_after_effect / delay / torn.
    at:       explicit 0-based hit indices of the matching seam at which to
              fire (empty = every hit, or probability `p` when set).
    p:        per-hit firing probability from the point's seeded stream
              (used only when `at` is empty).
    times:    max total firings (0 = unlimited — "repeated failure", the
              shape that must trip breaker/failover escalation).
    delay_s:  base sleep for mode="delay".
    jitter_s: extra uniform-[0, jitter_s) sleep from the seeded stream.
    match:    {ctx_key: substring} filters — the seam's context values
              (endpoint, addr, rid, ...) must contain every substring.
    """

    site: str
    mode: str = "abort"
    at: tuple[int, ...] = ()
    p: float = 0.0
    times: int = 1
    delay_s: float = 0.0
    jitter_s: float = 0.0
    match: dict[str, str] = field(default_factory=dict)

    def __post_init__(self) -> None:
        if self.mode not in _MODES:
            raise ValueError(f"unknown fault mode {self.mode!r}; one of {_MODES}")
        self.at = tuple(int(i) for i in self.at)


@dataclass
class FaultPlan:
    """A seed plus an ordered list of fault points."""

    seed: int = 0
    points: list[FaultPoint] = field(default_factory=list)

    @classmethod
    def from_json(cls, text: str, seed: int = 0) -> "FaultPlan":
        """Parse `[{"site": ..., "mode": ..., ...}, ...]` (the
        `FaultInjectionConfig.plan` wire format)."""
        data = json.loads(text)
        if isinstance(data, dict):
            seed = int(data.get("seed", seed))
            data = data.get("points", [])
        pts = []
        for d in data:
            d = dict(d)
            if "at" in d:
                d["at"] = tuple(d["at"])
            pts.append(FaultPoint(**d))
        return cls(seed=seed, points=pts)

    @classmethod
    def from_config(cls, cfg: Any) -> "FaultPlan | None":
        """Build from an `api.cli_args.FaultInjectionConfig`; None when
        disabled or the plan is empty."""
        if not getattr(cfg, "enabled", False):
            return None
        plan_text = getattr(cfg, "plan", "") or "[]"
        return cls.from_json(plan_text, seed=int(getattr(cfg, "seed", 0)))


class _Armed:
    """One fault point armed with its own deterministic RNG + counters."""

    __slots__ = ("point", "rng", "hits", "fired")

    def __init__(self, point: FaultPoint, seed: int, index: int):
        self.point = point
        # mix the plan seed with the point index so each point owns an
        # independent deterministic stream (tuple seeding is py<3.11 only)
        self.rng = random.Random(seed * 1_000_003 + index)
        self.hits = 0
        self.fired = 0


@dataclass
class _Action:
    mode: str
    point: FaultPoint
    sleep_s: float = 0.0
    tear_frac: float = 1.0


class FaultInjector:
    """Evaluates a FaultPlan at seam visits. Thread-safe: seams are hit
    from asyncio loops, the decode scheduler thread, and trainer threads;
    one lock serializes the hit counters and RNG draws."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._armed = [
            _Armed(p, plan.seed, i) for i, p in enumerate(plan.points)
        ]
        self._lock = threading.Lock()
        # (site, mode) -> fired count: the degradation evidence chaos
        # benches report next to recovery latency
        self.counters: dict[str, int] = {}

    # -- decision -------------------------------------------------------
    def _decide(
        self, site: str, ctx: dict[str, Any], modes: tuple[str, ...]
    ) -> _Action | None:
        """First matching armed point wins. A point's hit counter counts
        the visits that REACH it under an applicable entry point — points
        whose mode the entry point cannot express (`torn` at fire/afire,
        everything else at tear) are skipped without consuming a hit, and
        an earlier point that fires short-circuits the scan."""
        with self._lock:
            for a in self._armed:
                pt = a.point
                if pt.mode not in modes:
                    continue
                if not fnmatch.fnmatch(site, pt.site):
                    continue
                if any(
                    sub not in str(ctx.get(k, "")) for k, sub in pt.match.items()
                ):
                    continue
                hit = a.hits
                a.hits += 1
                if pt.times and a.fired >= pt.times:
                    continue
                if pt.at:
                    if hit not in pt.at:
                        continue
                elif pt.p > 0.0 and a.rng.random() >= pt.p:
                    continue
                a.fired += 1
                key = f"{site}|{pt.mode}"
                self.counters[key] = self.counters.get(key, 0) + 1
                sleep_s = pt.delay_s
                if pt.jitter_s > 0.0:
                    sleep_s += a.rng.uniform(0.0, pt.jitter_s)
                return _Action(
                    mode=pt.mode,
                    point=pt,
                    sleep_s=sleep_s,
                    tear_frac=a.rng.uniform(0.1, 0.9),
                )
        return None

    _FIRE_MODES = ("abort", "error_after_effect", "delay")

    # -- seam entry points ---------------------------------------------
    def fire(self, site: str, **ctx: Any) -> None:
        """Synchronous seam: sleep for delay faults, raise for aborts;
        torn points wait for the seam's `tear()` stage."""
        act = self._decide(site, ctx, self._FIRE_MODES)
        if act is None:
            return
        if act.mode == "delay":
            logger.warning(f"fault: delay {act.sleep_s:.3f}s at {site}")
            time.sleep(act.sleep_s)
            return
        logger.warning(f"fault: {act.mode} at {site} ({ctx})")
        raise InjectedFault(site, act.mode, act.point)

    async def afire(self, site: str, **ctx: Any) -> None:
        """Async seam twin of `fire` (delays await instead of blocking
        the event loop)."""
        act = self._decide(site, ctx, self._FIRE_MODES)
        if act is None:
            return
        if act.mode == "delay":
            logger.warning(f"fault: delay {act.sleep_s:.3f}s at {site}")
            await asyncio.sleep(act.sleep_s)
            return
        logger.warning(f"fault: {act.mode} at {site} ({ctx})")
        raise InjectedFault(site, act.mode, act.point)

    def tear(self, site: str, data, **ctx: Any):
        """Payload seam: a torn-mode point truncates `data` (str/bytes)
        at a seeded fraction; other modes are not considered here (they
        belong to fire/afire seams and keep their hit counters)."""
        act = self._decide(site, ctx, ("torn",))
        if act is None:
            return data
        cut = max(1, int(len(data) * act.tear_frac)) if len(data) else 0
        logger.warning(
            f"fault: torn payload at {site} ({len(data)} -> {cut} bytes)"
        )
        return data[:cut]

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            return dict(self.counters)


# -- process-global injector -------------------------------------------------

_INJECTOR: FaultInjector | None = None


def configure(plan: FaultPlan | FaultInjector | None) -> FaultInjector | None:
    """Install (or clear, with None) the process-global injector."""
    global _INJECTOR
    if plan is None:
        _INJECTOR = None
    elif isinstance(plan, FaultInjector):
        _INJECTOR = plan
    else:
        _INJECTOR = FaultInjector(plan)
    return _INJECTOR


def deactivate() -> None:
    configure(None)


def get() -> FaultInjector | None:
    """The active injector, or None. Seams use this as their fast path:
    `inj = fault_injection.get();  if inj is not None: inj.fire(...)`."""
    return _INJECTOR


def fire(site: str, **ctx: Any) -> None:
    if _INJECTOR is not None:
        _INJECTOR.fire(site, **ctx)


async def afire(site: str, **ctx: Any) -> None:
    if _INJECTOR is not None:
        await _INJECTOR.afire(site, **ctx)


def tear(site: str, data, **ctx: Any):
    if _INJECTOR is not None:
        return _INJECTOR.tear(site, data, **ctx)
    return data


def snapshot() -> dict[str, int]:
    return _INJECTOR.snapshot() if _INJECTOR is not None else {}
