"""Distributed rollout coordination (parity: areal/core/dist_rollout.py:43,93).

The reference runs rollout only on DP-head GPU ranks, then `redistribute()`
all-gathers trajectories across the DP group, slices them into GRPO groups,
FFD-balances groups by sequence length, and NCCL-broadcasts each rank's
slice to its CP/TP peers.

On TPU under single-controller SPMD the shape is different and simpler:

- rollout is a *host*-side activity (asyncio HTTP against decode servers) —
  every **process** (host) rolls out its share of the global batch; there is
  no per-device "DP head" because devices don't run Python.
- the gather step is a host-level all-gather over processes
  (jax.experimental.multihost_utils.process_allgather) instead of an NCCL
  all-gather over DP ranks.
- the "broadcast to CP/TP peers" step disappears entirely: handing the
  balanced global batch to `jax.device_put` with the engine's batch sharding
  places every row on exactly the devices that need it — XLA's runtime does
  the scatter.

What *survives* the translation is the balancing policy: GRPO groups stay
intact, and groups are placed into equal-cardinality per-DP-shard chunks
with near-equal token totals so no DP shard stalls on a long-tail batch
(the reference's FFD `_redistribute_by_group`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import numpy as np

from areal_tpu.utils import logging, stats_tracker
from areal_tpu.utils.data import concat_padded_tensors, get_batch_size
from areal_tpu.utils.datapack import reorder_to_balanced_batches

logger = logging.getLogger("dist_rollout")


@dataclasses.dataclass
class RedistributePlan:
    """Row order + per-DP-shard slices after balancing."""

    row_order: np.ndarray  # [B] original-row index for each new position
    shard_groups: list[list[int]]  # group indices per DP shard
    shard_tokens: list[int]  # token totals per DP shard (balance metric)


def redistribute(
    batch: dict[str, Any],
    *,
    group_size: int = 1,
    dp_size: int = 1,
) -> tuple[dict[str, Any], RedistributePlan]:
    """Reorder a padded [B, T] batch so contiguous B/dp_size row-slices have
    near-equal token totals, keeping each `group_size` block (one GRPO prompt
    group) intact. Rows of one shard stay contiguous, so the engine's
    dp-sharded `device_put` gives each DP shard its balanced slice.
    """
    B = get_batch_size(batch)
    assert B % group_size == 0, (B, group_size)
    n_groups = B // group_size
    assert n_groups % dp_size == 0, (
        f"groups ({n_groups}) must divide evenly over dp shards ({dp_size})"
    )
    am = np.asarray(batch["attention_mask"])
    group_lens = am.reshape(n_groups, group_size, -1).sum(axis=(1, 2))

    shard_groups = reorder_to_balanced_batches(group_lens, n_groups // dp_size)
    assert len(shard_groups) == dp_size, (len(shard_groups), dp_size)
    row_order = np.concatenate(
        [
            np.arange(g * group_size, (g + 1) * group_size)
            for groups in shard_groups
            for g in groups
        ]
    )
    out = {}
    for key, val in batch.items():
        arr = np.asarray(val)
        out[key] = arr[row_order] if arr.ndim >= 1 and arr.shape[0] == B else arr
    plan = RedistributePlan(
        row_order=row_order,
        shard_groups=shard_groups,
        shard_tokens=[int(group_lens[g].sum()) for g in shard_groups],
    )
    return out, plan


def _host_allgather(batch: dict[str, Any]) -> dict[str, Any]:
    """All-gather a padded batch across JAX processes (multi-host)."""
    import jax
    from jax.experimental import multihost_utils

    if jax.process_count() == 1:
        return batch
    # Align pad lengths across hosts, then gather along the batch axis.
    local_T = max(
        (np.asarray(v).shape[1] for v in batch.values() if np.asarray(v).ndim == 2),
        default=0,
    )
    max_T = int(
        multihost_utils.process_allgather(np.asarray([local_T])).max()
    )
    padded = {}
    for k, v in batch.items():
        arr = np.asarray(v)
        if arr.ndim == 2 and arr.shape[1] < max_T:
            arr = np.pad(arr, ((0, 0), (0, max_T - arr.shape[1])))
        padded[k] = arr
    gathered = multihost_utils.process_allgather(padded)
    # [P, B_local, ...] -> [P*B_local, ...]
    return {
        k: np.asarray(v).reshape((-1,) + np.asarray(v).shape[2:])
        for k, v in gathered.items()
    }


class DistRolloutCoordinator:
    """Couples a train engine with an inference engine's rollout queue and
    produces balanced global batches (parity: DistRolloutCoordinator,
    areal/core/dist_rollout.py:93 + FSDPEngine.prepare_batch fsdp_engine.py:482).
    """

    def __init__(
        self,
        train_engine,
        rollout_engine,
        *,
        allgather_fn: Callable[[dict[str, Any]], dict[str, Any]] | None = None,
    ):
        self.train_engine = train_engine
        self.rollout_engine = rollout_engine
        self._allgather = allgather_fn or _host_allgather

    def _dp_size(self) -> int:
        try:
            return int(self.train_engine.data_parallel_world_size())
        except Exception as e:  # noqa: BLE001 — single-process fallback
            logger.debug(f"dp size unavailable ({e!r}); assuming 1")
            return 1

    def prepare_batch(
        self,
        dataloader,
        *,
        granularity: int = 1,
        workflow=None,
        workflow_builder=None,
        should_accept=None,
    ) -> tuple[dict[str, Any], RedistributePlan]:
        """Pull one locally-rolled-out batch, gather across hosts, balance
        across DP shards. `granularity` is the GRPO group size — rows of one
        prompt group are kept on one shard."""
        with stats_tracker.record_timing("dist_rollout/local_rollout"):
            local = self.rollout_engine.prepare_batch(
                dataloader,
                workflow=workflow,
                workflow_builder=workflow_builder,
                should_accept=should_accept,
            )
        with stats_tracker.record_timing("dist_rollout/allgather"):
            global_batch = self._allgather(local)
        with stats_tracker.record_timing("dist_rollout/redistribute"):
            balanced, plan = redistribute(
                global_batch, group_size=granularity, dp_size=self._dp_size()
            )
        if len(plan.shard_tokens) > 1:
            logger.debug(
                f"redistributed: tokens/shard {plan.shard_tokens} "
                f"(imbalance {max(plan.shard_tokens) - min(plan.shard_tokens)})"
            )
        return balanced, plan

    def rollout_batch(
        self,
        data: list[dict[str, Any]],
        *,
        granularity: int = 1,
        workflow=None,
        workflow_builder=None,
        should_accept=None,
    ) -> tuple[dict[str, Any], RedistributePlan]:
        """Synchronous variant over an explicit item list."""
        local = self.rollout_engine.rollout_batch(
            data,
            workflow=workflow,
            workflow_builder=workflow_builder,
            should_accept=should_accept,
        )
        global_batch = self._allgather(local)
        return redistribute(
            global_batch, group_size=granularity, dp_size=self._dp_size()
        )


def merge_host_batches(batches: list[dict[str, Any]]) -> dict[str, Any]:
    """Concatenate per-host padded batches (test helper mirroring what
    process_allgather produces)."""
    return concat_padded_tensors(batches)
