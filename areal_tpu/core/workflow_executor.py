"""WorkflowExecutor: the asynchronous rollout pipeline driver.

Parity target: areal/core/workflow_executor.py:218 — submits workflow
episodes to the AsyncTaskRunner under StalenessManager capacity control,
validates trajectory format, applies `should_accept` filtering, and
assembles accepted trajectories into padded training batches.
`prepare_batch` keeps ≥ 2 training batches in flight (workflow_executor.py:
561-598) so the trainer never starves while staleness permits.
"""

from __future__ import annotations

import asyncio
import queue
import random
import time
from typing import TYPE_CHECKING, Any, Callable

import numpy as np

from areal_tpu.api.cli_args import InferenceEngineConfig
from areal_tpu.core.async_task_runner import AsyncTaskRunner, TaskResult
from areal_tpu.core.staleness_manager import StalenessManager
from areal_tpu.utils import logging, stats_tracker
from areal_tpu.utils.data import concat_padded_tensors, cycle_dataloader

if TYPE_CHECKING:
    from areal_tpu.api.engine_api import InferenceEngine
    from areal_tpu.api.workflow_api import RolloutWorkflow

logger = logging.getLogger("workflow_executor")


ROLLOUT_POLL_WAIT_TIME = 0.4


def check_trajectory_format(traj: dict[str, Any]) -> None:
    """Validate a workflow result batch (parity: workflow_executor.py:27).

    Requirements: dict of numpy arrays with a leading batch dim shared by
    all array values; must contain `attention_mask` and `input_ids` with
    matching [B, T] shapes.
    """
    if not isinstance(traj, dict) or not traj:
        raise ValueError(f"trajectory must be a non-empty dict, got {type(traj)}")
    if "input_ids" not in traj or "attention_mask" not in traj:
        raise ValueError(
            f"trajectory must contain input_ids and attention_mask, got "
            f"{sorted(traj.keys())}"
        )
    ii, am = np.asarray(traj["input_ids"]), np.asarray(traj["attention_mask"])
    if ii.ndim != 2 or am.shape != ii.shape:
        raise ValueError(
            f"input_ids/attention_mask must be matching [B, T], got "
            f"{ii.shape} vs {am.shape}"
        )
    bs = ii.shape[0]
    for k, v in traj.items():
        arr = np.asarray(v)
        if arr.ndim >= 1 and arr.shape[0] != bs:
            raise ValueError(
                f"trajectory key {k!r} batch dim {arr.shape[0]} != {bs}"
            )


class WorkflowExecutor:
    def __init__(
        self,
        config: InferenceEngineConfig,
        inference_engine: "InferenceEngine",
    ):
        self.config = config
        self.engine = inference_engine
        qsize = config.queue_size or 4096
        self.runner = AsyncTaskRunner(queue_size=qsize, name="rollout")
        max_concurrent = config.max_concurrent_rollouts or 64
        self.staleness_manager = StalenessManager(
            max_concurrent_rollouts=max_concurrent,
            consumer_batch_size=config.consumer_batch_size,
            max_staleness=config.max_head_offpolicyness,
        )
        # submissions deferred until staleness capacity admits them
        self._pending_inputs: queue.Queue = queue.Queue(maxsize=qsize)
        self._result_cache: list[dict[str, Any]] = []
        self._data_generator = None
        self._version = 0
        self._paused = False
        self._consecutive_failures = 0

    # -- lifecycle ------------------------------------------------------
    def initialize(self, train_data_parallel_size: int | None = None) -> None:
        self.runner.start()

    def destroy(self) -> None:
        self.runner.destroy()

    # -- versioning -----------------------------------------------------
    def set_version(self, version: int) -> None:
        self._version = version

    def get_version(self) -> int:
        return self._version

    # -- flow control ---------------------------------------------------
    def pause(self) -> None:
        """Stop admitting new rollouts (weight-update window)."""
        self._paused = True
        self.runner.pause()

    def resume(self) -> None:
        self._paused = False
        self.runner.resume()

    @property
    def paused(self) -> bool:
        return self._paused

    # -- submission -----------------------------------------------------
    def submit(
        self,
        data: dict[str, Any],
        workflow: "RolloutWorkflow | None" = None,
        workflow_builder: Callable | None = None,
        should_accept: Callable | None = None,
    ) -> None:
        """Queue one episode; actual launch happens when capacity allows."""
        assert workflow is not None or workflow_builder is not None
        try:
            self._pending_inputs.put_nowait(
                (data, workflow, workflow_builder, should_accept)
            )
        except queue.Full:
            raise RuntimeError("workflow executor input queue full") from None

    def _launch_one(self, item) -> None:
        data, workflow, workflow_builder, should_accept = item
        if workflow is None:
            workflow = workflow_builder()
        sm = self.staleness_manager
        engine = self.engine
        tracing = self.config.enable_rollout_tracing
        check_format = self.config.check_trajectory_format

        async def episode():
            traj = await workflow.arun_episode(engine, data)
            if traj is not None and check_format:
                check_trajectory_format(traj)
            if traj is not None and should_accept is not None and not should_accept(traj):
                traj = None
            return traj

        task_id = self.runner.submit(episode)
        sm.on_rollout_submitted()
        if tracing:
            logger.info(f"submitted rollout task {task_id}")

    def _admit_pending(self) -> None:
        """Move pending submissions into the runner within capacity."""
        if self._paused:
            return
        capacity = self.staleness_manager.get_capacity(self._version)
        while capacity > 0:
            try:
                item = self._pending_inputs.get_nowait()
            except queue.Empty:
                return
            self._launch_one(item)
            capacity -= 1

    def _collect(self) -> None:
        results = self.runner.poll_results()
        for i, tr in enumerate(results):
            try:
                self._on_result(tr)
            except BaseException:
                # the failure-streak escalation raises out of here; the
                # drained-but-unprocessed tail still owns running slots —
                # requeue it so the accounting stays collectable instead
                # of leaking with the dropped list
                self.runner.requeue_results(results[i + 1:])
                raise

    def _on_result(self, tr: TaskResult) -> None:
        sm = self.staleness_manager
        if tr.exception is not None:
            # whatever killed the episode, its capacity slot is released
            # exactly once here — the runner guarantees one TaskResult per
            # task (including cancelled ones), so `running` can neither
            # leak nor double-release on a cancel-then-fail race
            sm.on_rollout_rejected()
            if isinstance(tr.exception, asyncio.CancelledError):
                # a drained (pause/shutdown) episode is not evidence of a
                # sick engine — release the slot but don't feed the
                # consecutive-failure escalation
                return
            # A systematic failure (e.g. crashed decode engine) must surface
            # instead of spinning forever resubmitting doomed episodes.
            self._consecutive_failures += 1
            if self._consecutive_failures >= 16:
                raise RuntimeError(
                    "16 consecutive rollout episodes failed; last error"
                ) from tr.exception
            return
        # any completed episode (accepted or rejected) breaks the streak
        self._consecutive_failures = 0
        traj = tr.result
        if traj is None:
            sm.on_rollout_rejected()
            if self.config.enable_rollout_tracing:
                logger.info(f"rollout {tr.task_id} rejected")
            return
        sm.on_rollout_accepted()
        self._result_cache.append(traj)

    # -- collection -----------------------------------------------------
    def wait(self, count: int, timeout: float | None = None) -> dict[str, Any]:
        """Block until `count` accepted trajectories exist; returns their
        concatenation as one padded batch."""
        deadline = (
            time.monotonic() + (timeout if timeout is not None else 3600.0)
        )
        while len(self._result_cache) < count:
            self.runner.health_check()
            self._admit_pending()
            self._collect()
            if len(self._result_cache) >= count:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"wait({count}): only {len(self._result_cache)} accepted"
                )
            time.sleep(ROLLOUT_POLL_WAIT_TIME / 100)
        results, self._result_cache = (
            self._result_cache[:count],
            self._result_cache[count:],
        )
        # Shuffle so GRPO groups from the same prompt don't correlate with
        # batch position (parity: workflow_executor wait shuffles).
        random.shuffle(results)
        return concat_padded_tensors(results)

    def rollout_batch(
        self,
        data: list[dict[str, Any]],
        workflow: "RolloutWorkflow | None" = None,
        workflow_builder: Callable | None = None,
        should_accept: Callable | None = None,
    ) -> dict[str, Any]:
        """Synchronous batch rollout: submit all, wait for all."""
        for item in data:
            self.submit(item, workflow, workflow_builder, should_accept)
        return self.wait(count=len(data))

    def prepare_batch(
        self,
        dataloader,
        workflow: "RolloutWorkflow | None" = None,
        workflow_builder: Callable | None = None,
        should_accept: Callable | None = None,
    ) -> dict[str, Any]:
        """Async pipeline heart: keep ≥2 batches of episodes in flight and
        return one training batch when ready (workflow_executor.py:561-598)."""
        if self._data_generator is None:
            self._data_generator = cycle_dataloader(dataloader)
        batch_size = dataloader.batch_size
        assert batch_size is not None
        while True:
            self.runner.health_check()
            capacity = self.staleness_manager.get_capacity(self._version)
            pending_total = (
                self._pending_inputs.qsize()
                + self.runner.inflight
                + len(self._result_cache)
            )
            # keep two batches in the pipeline
            if capacity + batch_size > 0 and pending_total < 2 * batch_size:
                items = next(self._data_generator)
                if isinstance(items, dict):
                    items = [items]
                for item in items:
                    self.submit(item, workflow, workflow_builder, should_accept)
            self._admit_pending()
            self._collect()
            if len(self._result_cache) >= batch_size:
                with stats_tracker.record_timing("prepare_batch/concat"):
                    return self.wait(batch_size, timeout=1)
            time.sleep(ROLLOUT_POLL_WAIT_TIME / 10)

    def get_stats(self):
        return self.staleness_manager.get_stats()
