"""WorkflowExecutor: the asynchronous rollout pipeline driver.

Parity target: areal/core/workflow_executor.py:218 — submits workflow
episodes to the AsyncTaskRunner under StalenessManager capacity control,
validates trajectory format, applies `should_accept` filtering, and
assembles accepted trajectories into padded training batches.
`prepare_batch` keeps ≥ 2 training batches in flight (workflow_executor.py:
561-598) so the trainer never starves while staleness permits.
"""

from __future__ import annotations

import asyncio
import queue
import random
import time
from typing import TYPE_CHECKING, Any, Callable

import numpy as np

from areal_tpu.api.cli_args import InferenceEngineConfig
from areal_tpu.core.async_task_runner import AsyncTaskRunner, TaskResult
from areal_tpu.core.sample_ledger import SampleLedger, SampleWAL
from areal_tpu.core.staleness_manager import StalenessManager
from areal_tpu.utils import logging, stats_tracker
from areal_tpu.utils.data import concat_padded_tensors, cycle_dataloader

if TYPE_CHECKING:
    from areal_tpu.api.engine_api import InferenceEngine
    from areal_tpu.api.workflow_api import RolloutWorkflow

logger = logging.getLogger("workflow_executor")


ROLLOUT_POLL_WAIT_TIME = 0.4


def check_trajectory_format(traj: dict[str, Any]) -> None:
    """Validate a workflow result batch (parity: workflow_executor.py:27).

    Requirements: dict of numpy arrays with a leading batch dim shared by
    all array values; must contain `attention_mask` and `input_ids` with
    matching [B, T] shapes.
    """
    if not isinstance(traj, dict) or not traj:
        raise ValueError(f"trajectory must be a non-empty dict, got {type(traj)}")
    if "input_ids" not in traj or "attention_mask" not in traj:
        raise ValueError(
            f"trajectory must contain input_ids and attention_mask, got "
            f"{sorted(traj.keys())}"
        )
    ii, am = np.asarray(traj["input_ids"]), np.asarray(traj["attention_mask"])
    if ii.ndim != 2 or am.shape != ii.shape:
        raise ValueError(
            f"input_ids/attention_mask must be matching [B, T], got "
            f"{ii.shape} vs {am.shape}"
        )
    bs = ii.shape[0]
    for k, v in traj.items():
        arr = np.asarray(v)
        if arr.ndim >= 1 and arr.shape[0] != bs:
            raise ValueError(
                f"trajectory key {k!r} batch dim {arr.shape[0]} != {bs}"
            )


class WorkflowExecutor:
    def __init__(
        self,
        config: InferenceEngineConfig,
        inference_engine: "InferenceEngine",
    ):
        self.config = config
        self.engine = inference_engine
        qsize = config.queue_size or 4096
        self.runner = AsyncTaskRunner(queue_size=qsize, name="rollout")
        max_concurrent = config.max_concurrent_rollouts or 64
        self.staleness_manager = StalenessManager(
            max_concurrent_rollouts=max_concurrent,
            consumer_batch_size=config.consumer_batch_size,
            max_staleness=config.max_head_offpolicyness,
        )
        # submissions deferred until staleness capacity admits them
        self._pending_inputs: queue.Queue = queue.Queue(maxsize=qsize)
        self._result_cache: list[dict[str, Any]] = []
        self._data_generator = None
        self._version = 0
        self._paused = False
        self._consecutive_failures = 0
        # exactly-once sample accounting: rollout-id issuance, consumed-id
        # dedup, and the consumed-batch WAL (core/sample_ledger.py)
        self.ledger = SampleLedger()

    # -- lifecycle ------------------------------------------------------
    def initialize(self, train_data_parallel_size: int | None = None) -> None:
        self.runner.start()

    def destroy(self) -> None:
        self.runner.destroy()

    # -- versioning -----------------------------------------------------
    def set_version(self, version: int) -> None:
        self._version = version

    def get_version(self) -> int:
        return self._version

    # -- flow control ---------------------------------------------------
    def pause(self) -> None:
        """Stop admitting new rollouts (weight-update window)."""
        self._paused = True
        self.runner.pause()

    def resume(self) -> None:
        self._paused = False
        self.runner.resume()

    @property
    def paused(self) -> bool:
        return self._paused

    # -- submission -----------------------------------------------------
    def submit(
        self,
        data: dict[str, Any],
        workflow: "RolloutWorkflow | None" = None,
        workflow_builder: Callable | None = None,
        should_accept: Callable | None = None,
        rollout_id: int | None = None,
    ) -> None:
        """Queue one episode; actual launch happens when capacity allows.

        `rollout_id` gives the episode a caller-chosen stable identity
        (deterministic resubmission after a trainer restart regenerates
        the same ids, so the ledger can dedup); default is the ledger's
        next monotone id."""
        assert workflow is not None or workflow_builder is not None
        rid = self.ledger.new_rid() if rollout_id is None else int(rollout_id)
        try:
            self._pending_inputs.put_nowait(
                (rid, data, workflow, workflow_builder, should_accept)
            )
        except queue.Full:
            raise RuntimeError("workflow executor input queue full") from None

    def _launch_one(self, item) -> None:
        rid, data, workflow, workflow_builder, should_accept = item
        if workflow is None:
            workflow = workflow_builder()
        sm = self.staleness_manager
        engine = self.engine
        tracing = self.config.enable_rollout_tracing
        check_format = self.config.check_trajectory_format

        async def episode():
            traj = await workflow.arun_episode(engine, data)
            if traj is not None and check_format:
                check_trajectory_format(traj)
            if traj is not None and should_accept is not None and not should_accept(traj):
                traj = None
            return rid, traj

        task_id = self.runner.submit(episode)
        sm.on_rollout_submitted()
        if tracing:
            logger.info(f"submitted rollout task {task_id} (rid {rid})")

    def _admit_pending(self) -> None:
        """Move pending submissions into the runner within capacity."""
        if self._paused:
            return
        capacity = self.staleness_manager.get_capacity(self._version)
        while capacity > 0:
            try:
                item = self._pending_inputs.get_nowait()
            except queue.Empty:
                return
            self._launch_one(item)
            capacity -= 1

    def _collect(self) -> None:
        results = self.runner.poll_results()
        for i, tr in enumerate(results):
            try:
                self._on_result(tr)
            except BaseException:
                # the failure-streak escalation raises out of here; the
                # drained-but-unprocessed tail still owns running slots —
                # requeue it so the accounting stays collectable instead
                # of leaking with the dropped list
                self.runner.requeue_results(results[i + 1:])
                raise

    def _on_result(self, tr: TaskResult) -> None:
        sm = self.staleness_manager
        if tr.exception is not None:
            # whatever killed the episode, its capacity slot is released
            # exactly once here — the runner guarantees one TaskResult per
            # task (including cancelled ones), so `running` can neither
            # leak nor double-release on a cancel-then-fail race
            sm.on_rollout_rejected()
            if isinstance(tr.exception, asyncio.CancelledError):
                # a drained (pause/shutdown) episode is not evidence of a
                # sick engine — release the slot but don't feed the
                # consecutive-failure escalation
                return
            # A systematic failure (e.g. crashed decode engine) must surface
            # instead of spinning forever resubmitting doomed episodes.
            self._consecutive_failures += 1
            if self._consecutive_failures >= 16:
                # embed the root cause in the message itself — operators see
                # the raised line long before they dig for the __cause__
                raise RuntimeError(
                    f"16 consecutive rollout episodes failed; last error: "
                    f"{tr.exception!r}"
                ) from tr.exception
            return
        # any completed episode (accepted or rejected) breaks the streak
        self._consecutive_failures = 0
        rid, traj = tr.result
        if traj is None:
            sm.on_rollout_rejected()
            if self.config.enable_rollout_tracing:
                logger.info(f"rollout {tr.task_id} (rid {rid}) rejected")
            return
        if not self.ledger.on_accepted(rid, self._version):
            # already consumed (or already pending) — a duplicate from a
            # still-running replica after a trainer restart; training on it
            # again would double-count the sample
            sm.on_rollout_rejected()
            logger.info(f"rollout rid {rid} deduped (already in ledger)")
            return
        sm.on_rollout_accepted()
        # stamp identity so the batch carries provenance through
        # concat/microbatching and wait() can journal what it consumed
        key0 = "input_ids" if "input_ids" in traj else next(iter(traj))
        bs = int(np.asarray(traj[key0]).shape[0])
        traj["rollout_id"] = np.full((bs,), rid, dtype=np.int64)
        traj["rollout_version"] = np.full((bs,), self._version, dtype=np.int64)
        self._result_cache.append(traj)

    # -- collection -----------------------------------------------------
    def wait(self, count: int, timeout: float | None = None) -> dict[str, Any]:
        """Block until `count` accepted trajectories exist; returns their
        concatenation as one padded batch."""
        deadline = (
            time.monotonic() + (timeout if timeout is not None else 3600.0)
        )
        while len(self._result_cache) < count:
            self.runner.health_check()
            self._admit_pending()
            self._collect()
            if len(self._result_cache) >= count:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"wait({count}): only {len(self._result_cache)} accepted"
                )
            time.sleep(ROLLOUT_POLL_WAIT_TIME / 100)
        results, self._result_cache = (
            self._result_cache[:count],
            self._result_cache[count:],
        )
        # journal the consumed batch BEFORE handing it to the trainer: the
        # WAL entry is durable by the time any weight update can depend on
        # these samples, so crash recovery can tell trained from lost
        rids = [int(np.asarray(r["rollout_id"]).flat[0]) for r in results
                if "rollout_id" in r]
        if rids:
            self.ledger.on_consumed(rids, self._version)
        # Shuffle so GRPO groups from the same prompt don't correlate with
        # batch position (parity: workflow_executor wait shuffles).
        random.shuffle(results)
        return concat_padded_tensors(results)

    def rollout_batch(
        self,
        data: list[dict[str, Any]],
        workflow: "RolloutWorkflow | None" = None,
        workflow_builder: Callable | None = None,
        should_accept: Callable | None = None,
    ) -> dict[str, Any]:
        """Synchronous batch rollout: submit all, wait for all."""
        for item in data:
            self.submit(item, workflow, workflow_builder, should_accept)
        return self.wait(count=len(data))

    def prepare_batch(
        self,
        dataloader,
        workflow: "RolloutWorkflow | None" = None,
        workflow_builder: Callable | None = None,
        should_accept: Callable | None = None,
    ) -> dict[str, Any]:
        """Async pipeline heart: keep ≥2 batches of episodes in flight and
        return one training batch when ready (workflow_executor.py:561-598)."""
        if self._data_generator is None:
            self._data_generator = cycle_dataloader(dataloader)
        batch_size = dataloader.batch_size
        assert batch_size is not None
        while True:
            self.runner.health_check()
            capacity = self.staleness_manager.get_capacity(self._version)
            pending_total = (
                self._pending_inputs.qsize()
                + self.runner.inflight
                + len(self._result_cache)
            )
            # keep two batches in the pipeline
            if capacity + batch_size > 0 and pending_total < 2 * batch_size:
                items = next(self._data_generator)
                if isinstance(items, dict):
                    items = [items]
                for item in items:
                    self.submit(item, workflow, workflow_builder, should_accept)
            self._admit_pending()
            self._collect()
            if len(self._result_cache) >= batch_size:
                with stats_tracker.record_timing("prepare_batch/concat"):
                    return self.wait(batch_size, timeout=1)
            time.sleep(ROLLOUT_POLL_WAIT_TIME / 10)

    def get_stats(self):
        return self.staleness_manager.get_stats()

    # -- checkpointing ---------------------------------------------------
    def attach_ledger_wal(self, path: str) -> None:
        """Journal consumed batches to a WAL at `path` (colocated with the
        recover checkpoints; see utils/recover.ledger_wal_path)."""
        self.ledger.attach_wal(SampleWAL(path))

    def state_dict(self) -> dict[str, Any]:
        """Sample-ledger + staleness accounting, committed inside the
        recover checkpoint (RecoverInfo.ledger_info)."""
        return dict(
            ledger=self.ledger.state_dict(),
            staleness=self.staleness_manager.state_dict(),
        )

    def load_state_dict(self, state: dict[str, Any]) -> None:
        """Restore after a trainer crash. The staleness cap is recomputed
        from the ledger: `accepted` := consumed count (cached-but-
        unconsumed trajectories died with the process and will be
        regenerated — restoring the raw accepted counter would permanently
        shrink capacity by the lost cache), `running` := 0 (nothing is in
        flight in a fresh process). The attached WAL is rolled back to the
        committed sequence inside ledger.load_state_dict."""
        self.ledger.load_state_dict(state.get("ledger", {}))
        consumed = self.ledger.consumed_count()
        sm_state = dict(state.get("staleness", {}))
        sm_state["accepted"] = consumed
        sm_state["running"] = 0
        sm_state["submitted"] = max(int(sm_state.get("submitted", 0)), consumed)
        self.staleness_manager.load_state_dict(sm_state)
        self._result_cache = []
        self._consecutive_failures = 0
