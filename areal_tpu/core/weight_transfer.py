"""Learner → decode-server weight transfer wire format (the "dcn" path).

The reference's fast path broadcasts parameters over a dedicated NCCL group
spanning trainer rank-0 + all inference workers, bucketed in ~1 GiB chunks
with a param-spec manifest sent over HTTP first (fsdp_engine.py:298-401,
io_struct.py WeightUpdateMeta/ParamSpec). TPU pods have no NCCL; the
learner↔decode link is DCN, and the natural transport is the same HTTP
control plane the decode servers already speak.

Wire format per bucket (one POST body):

    [8 bytes little-endian manifest length][manifest JSON][raw tensor bytes]

The manifest lists {name, shape, dtype, offset, nbytes} per tensor; tensor
bytes are the arrays' native layouts concatenated — bfloat16 stays bfloat16
on the wire (half the bytes of the safetensors-numpy fallback, which cannot
store bf16). Buckets are capped at `chunk_mb` (parity: the reference's
weight_chunked_mem_mb) so server memory stays bounded and transfers
pipeline across servers.
"""

from __future__ import annotations

import collections
import json
import struct
from typing import Any, Iterable, Iterator

import numpy as np

from areal_tpu.utils import logging

logger = logging.getLogger("weight_transfer")

try:  # ml_dtypes ships with jax; gives numpy a bfloat16 dtype
    import ml_dtypes

    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BFLOAT16 = None


def _np_dtype(name: str) -> np.dtype:
    if name == "bfloat16":
        assert _BFLOAT16 is not None, "bfloat16 wire format needs ml_dtypes"
        return _BFLOAT16
    return np.dtype(name)


def _dtype_name(dt: np.dtype) -> str:
    if _BFLOAT16 is not None and dt == _BFLOAT16:
        return "bfloat16"
    return dt.name


def flatten_named(tree: Any, prefix: tuple[str, ...] = ()) -> dict[str, np.ndarray]:
    """Param pytree → {"a/b/c": ndarray} (host numpy, original dtype)."""
    out: dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(flatten_named(v, prefix + (str(k),)))
    else:
        out["/".join(prefix)] = np.asarray(tree)
    return out


def named_leaves(
    tree: Any, prefix: tuple[str, ...] = ()
) -> Iterator[tuple[str, Any]]:
    """Lazy flatten_named: yield ("a/b/c", leaf) WITHOUT converting leaves.

    The pipelined push path needs the names before it touches the bytes —
    np.asarray on a jax.Array blocks on a device→host copy, and doing that
    eagerly for the whole tree (what flatten_named does) serializes the
    transfer behind the first HTTP POST."""
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from named_leaves(v, prefix + (str(k),))
    else:
        yield "/".join(prefix), tree


def iter_prefetched(
    items: Iterable[tuple[str, Any]], window: int = 2
) -> Iterator[tuple[str, np.ndarray]]:
    """Yield (name, host ndarray) with the NEXT `window` device→host copies
    already in flight (jax.Array.copy_to_host_async). While the consumer
    packs/POSTs tensor N, the DMA for tensors N+1..N+window runs in the
    background — the double-buffering half of the pipelined weight push.
    Non-jax leaves pass through np.asarray unchanged."""
    window = max(int(window), 1)
    pending: collections.deque[tuple[str, Any]] = collections.deque()

    def _start(name: str, leaf: Any) -> tuple[str, Any]:
        start = getattr(leaf, "copy_to_host_async", None)
        if start is not None:
            try:
                start()
            except Exception as e:  # pragma: no cover - backend-dependent
                logger.debug(f"copy_to_host_async unavailable: {e!r}")
        return name, leaf

    for name, leaf in items:
        pending.append(_start(name, leaf))
        if len(pending) > window:
            n, l = pending.popleft()
            yield n, np.asarray(l)
    while pending:
        n, l = pending.popleft()
        yield n, np.asarray(l)


def set_named(tree: Any, named: dict[str, np.ndarray], cast=None) -> Any:
    """Replace leaves of `tree` by name; unknown names error, missing names
    keep the old leaf. Returns a new tree of the same structure."""
    used: set[str] = set()

    def walk(node, prefix):
        if isinstance(node, dict):
            return {k: walk(v, prefix + (str(k),)) for k, v in node.items()}
        name = "/".join(prefix)
        if name in named:
            used.add(name)
            val = named[name]
            return cast(val, node) if cast is not None else val
        return node

    new = walk(tree, ())
    unknown = set(named) - used
    if unknown:
        raise KeyError(f"weight names not in target tree: {sorted(unknown)[:5]}")
    return new


def pack_buckets(
    named: dict[str, np.ndarray] | Iterable[tuple[str, Any]],
    chunk_mb: float = 512,
) -> Iterable[bytes]:
    """Yield framed bucket payloads, each <= chunk_mb. Tensors larger than
    one bucket are split into byte-range parts (part_offset/total_nbytes in
    the manifest) so no single HTTP body ever exceeds the limit — a 2.5 GiB
    embedding streams as five 512 MiB frames. Yielding lazily keeps peak
    extra host memory at one bucket.

    `named` may be a dict or any (name, array) iterable — the pipelined push
    feeds a prefetching generator (iter_prefetched) so device→host copies
    overlap the HTTP POSTs downstream. Tensor bytes are sliced through a
    zero-copy uint8 view, so a split tensor never duplicates its full buffer
    (the old `arr.tobytes()` doubled peak host memory for the largest
    param)."""
    limit = max(int(chunk_mb * 1024 * 1024), 1)
    manifest: list[dict] = []
    chunks: list[Any] = []  # bytes-likes (memoryview slices)
    size = 0

    def flush():
        nonlocal manifest, chunks, size
        mjson = json.dumps(manifest).encode()
        payload = struct.pack("<Q", len(mjson)) + mjson + b"".join(chunks)
        manifest, chunks, size = [], [], 0
        return payload

    items = named.items() if hasattr(named, "items") else named
    for name, arr in items:
        arr = np.ascontiguousarray(arr)
        # flat byte view: slicing it below is zero-copy; the only copy is
        # the b"".join into the outgoing frame
        raw = memoryview(arr.reshape(-1).view(np.uint8))
        total = arr.nbytes
        part_off = 0
        while True:
            take = min(limit - size, total - part_off)
            manifest.append(
                dict(
                    name=name,
                    shape=list(arr.shape),
                    dtype=_dtype_name(arr.dtype),
                    offset=size,
                    nbytes=take,
                    part_offset=part_off,
                    total_nbytes=total,
                )
            )
            chunks.append(raw[part_off : part_off + take])
            size += take
            part_off += take
            if size >= limit:
                yield flush()
            if part_off >= total:
                break
    if manifest:
        yield flush()


def raw_wire_nbytes(name: str, nbytes: int, dtype: str) -> int:
    """bf16-equivalent wire cost of one tensor (or tensor part): what the
    bytes WOULD have been had the push shipped fp kernels. A producer-
    quantized kernel's `.../q` leaf replaces a bf16 tensor of the same
    element count (2 bytes vs its 1-byte int8), and its `.../scale`
    sibling would not exist on the fp wire at all; everything else ships
    identically. raw/sent is the weight-sync compression ratio surfaced
    by client get_metrics() and the servers' /metrics.weight_sync."""
    leaf = name.rsplit("/", 1)[-1]
    if leaf == "q" and dtype == "int8":
        return nbytes * 2
    if leaf == "scale" and dtype == "float32":
        return 0
    return nbytes


def frame_raw_nbytes(payload: bytes) -> int:
    """Sum raw_wire_nbytes over one framed bucket's manifest (parts of a
    split tensor each count their own share). Assumes the frame already
    passed unpack_bucket_parts' torn-frame checks."""
    (mlen,) = struct.unpack_from("<Q", payload, 0)
    manifest = json.loads(payload[8 : 8 + mlen].decode())
    return sum(
        raw_wire_nbytes(s["name"], s["nbytes"], s["dtype"]) for s in manifest
    )


def unpack_bucket_parts(payload: bytes) -> list[tuple[dict, bytes]]:
    """One frame → [(spec, raw_bytes)] — parts of possibly-split tensors.

    Raises ValueError on a TORN frame (body shorter than the manifest
    declares): silently staging a short part would count phantom coverage
    and either materialize a corrupt tensor or wedge the push at finalize.
    An exception here turns into a 5xx, and the client's bucket retry
    re-sends the full frame."""
    if len(payload) < 8:
        raise ValueError(f"torn weight frame: {len(payload)} bytes, no header")
    (mlen,) = struct.unpack_from("<Q", payload, 0)
    if len(payload) < 8 + mlen:
        raise ValueError(
            f"torn weight frame: manifest needs {8 + mlen} bytes, "
            f"got {len(payload)}"
        )
    manifest = json.loads(payload[8 : 8 + mlen].decode())
    base = 8 + mlen
    need = max((s["offset"] + s["nbytes"] for s in manifest), default=0)
    if len(payload) < base + need:
        raise ValueError(
            f"torn weight frame: body needs {need} tensor bytes, "
            f"got {len(payload) - base}"
        )
    return [
        (spec, payload[base + spec["offset"] : base + spec["offset"] + spec["nbytes"]])
        for spec in manifest
    ]


def _merge_interval(
    intervals: list[tuple[int, int]], start: int, end: int
) -> list[tuple[int, int]]:
    """Insert [start, end) into sorted disjoint intervals, coalescing
    overlaps and adjacency. O(n) with n = number of disjoint ranges (small:
    parts arrive mostly in order, so n rarely exceeds 2)."""
    out: list[tuple[int, int]] = []
    placed = False
    for s, e in intervals:
        if e < start or s > end:  # strictly disjoint (not even adjacent)
            if s > end and not placed:
                out.append((start, end))
                placed = True
            out.append((s, e))
        else:  # overlap or touch: absorb into the new interval
            start, end = min(s, start), max(e, end)
    if not placed:
        out.append((start, end))
        out.sort()
    return out


class WeightStaging:
    """Server-side accumulator: feed it frames in any order; tensors
    materialise once all their byte ranges have arrived.

    Duplicate frames are EXPECTED: the client's arequest_with_retry re-sends
    a bucket whenever a response times out even though the server may have
    already applied it. Received coverage is therefore tracked as MERGED
    byte intervals — duplicates and partial overlaps count each byte once.
    (A plain sum over (offset, nbytes) pairs double-counts overlapping
    ranges: a retry that re-splits a tensor differently could materialise a
    tensor with holes.) Parts of a tensor that already materialised are
    dropped outright."""

    def __init__(self):
        self._bufs: dict[str, bytearray] = {}
        self._meta: dict[str, dict] = {}
        # per tensor: sorted, disjoint [start, end) intervals of received bytes
        self._parts: dict[str, list[tuple[int, int]]] = {}
        self.ready: dict[str, np.ndarray] = {}

    def __len__(self) -> int:
        return len(self.ready)

    def reset(self) -> None:
        """Drop all staged state (start of a new push / failed commit)."""
        self._bufs.clear()
        self._meta.clear()
        self._parts.clear()
        self.ready.clear()

    def add_bucket(self, payload: bytes) -> None:
        from areal_tpu.core import fault_injection

        # staging seam: an abort models a frame lost between HTTP receive
        # and staging apply; a torn frame truncates the payload, which the
        # manifest length-check below rejects — either way the client's
        # bucket retry re-covers the byte ranges (interval-merged, so a
        # re-split retry can never materialize a tensor with holes)
        fault_injection.fire("weight.stage.add", nbytes=len(payload))
        payload = fault_injection.tear("weight.stage.add", payload)
        for spec, raw in unpack_bucket_parts(payload):
            name = spec["name"]
            if name in self.ready:  # duplicate of a completed tensor
                continue
            total = spec["total_nbytes"]
            if name not in self._bufs:
                self._bufs[name] = bytearray(total)
                self._meta[name] = spec
                self._parts[name] = []
            off = spec["part_offset"]
            self._bufs[name][off : off + len(raw)] = raw
            self._parts[name] = _merge_interval(
                self._parts[name], off, off + len(raw)
            )
            covered = sum(e - s for s, e in self._parts[name])
            if covered >= total:
                m = self._meta[name]
                self.ready[name] = np.frombuffer(
                    bytes(self._bufs.pop(name)), dtype=_np_dtype(m["dtype"])
                ).reshape(m["shape"])
                self._meta.pop(name)
                self._parts.pop(name)

    def finalize(self) -> dict[str, np.ndarray]:
        if self._bufs:
            raise RuntimeError(
                f"incomplete weight transfer: missing bytes for "
                f"{sorted(self._bufs)[:5]}"
            )
        out, self.ready = self.ready, {}
        return out


def unpack_bucket(payload: bytes) -> dict[str, np.ndarray]:
    """Single-frame convenience: all parts must be complete in this frame."""
    st = WeightStaging()
    st.add_bucket(payload)
    return st.finalize()


# -- KV-session wire format (disaggregated prefill/decode, ISSUE 10) --------
#
# A migrated session rides the SAME framed-bucket plumbing as a weight push:
# interval-merged staging absorbs duplicate/re-split retry frames, the
# manifest length-checks reject torn frames before a byte is staged, and
# multi-frame splitting bounds every HTTP body. The "tensors" of a session
# are its gathered pool blocks (K and V, [L, nb, block_size, nKV, hd]) plus
# one JSON metadata blob carried as a uint8 tensor — exactly the
# `HostKVEntry` resume contract (rid, covered token list, rope_delta,
# sampling base key, weight version), so an imported session promotes
# through the host-tier swap-in seam bit-identically to a local offload.

KV_META_PREFIX = "kvmeta/"
KV_DATA_PREFIX = "kvdata/"

# HostKVEntry fields the wire metadata must carry for an exact resume.
# `kv_dtype` is optional-with-default on READ ("fp") so pre-quantization
# senders stay decodable; int8 sessions always stamp it and additionally
# ship their per-row scale blocks as .../ks and .../vs tensors.
_KV_META_REQUIRED = (
    "rid", "covered", "tokens", "rope_delta", "base_key", "weight_version",
    "nb",
)


def pack_kv_session(
    meta: dict,
    k: np.ndarray,
    v: np.ndarray,
    ks: np.ndarray | None = None,
    vs: np.ndarray | None = None,
    chunk_mb: float = 64,
) -> Iterable[bytes]:
    """Frame one session's KV blocks + resume metadata as wire buckets.

    `meta` must carry the HostKVEntry resume contract (see
    _KV_META_REQUIRED); `k`/`v` are the session's gathered pool blocks —
    for an int8 session (meta["kv_dtype"] == "int8") the int8 bytes
    VERBATIM, with the f32 scale blocks in `ks`/`vs`. The wire never
    requantizes: the session's pool bytes ARE the payload, which is what
    halves migration traffic for quantized fleets. The metadata travels
    first so a receiver that streams frames in order can validate the
    session before most of the bytes arrive (staging itself is
    order-independent)."""
    missing = [f for f in _KV_META_REQUIRED if f not in meta]
    if missing:
        raise ValueError(f"kv session meta missing fields: {missing}")
    rid = str(meta["rid"])
    if meta.get("meta_only"):
        # cheap-drain shape (fleet KV fabric): identity + sampling key
        # only — the fleet holds the blocks, so the wire carries none
        if k is not None or v is not None or ks is not None:
            raise ValueError(
                f"meta-only kv session {rid!r} must not carry blocks"
            )
        mjson = np.frombuffer(
            json.dumps(meta, sort_keys=True).encode(), dtype=np.uint8
        )
        yield from pack_buckets(
            [(f"{KV_META_PREFIX}{rid}", mjson)], chunk_mb=chunk_mb
        )
        return
    if (str(meta.get("kv_dtype", "fp")) == "int8") != (ks is not None):
        raise ValueError(
            "kv session scales must travel iff meta kv_dtype == 'int8' "
            f"(kv_dtype={meta.get('kv_dtype', 'fp')!r}, "
            f"scales={'present' if ks is not None else 'absent'})"
        )
    mjson = np.frombuffer(
        json.dumps(meta, sort_keys=True).encode(), dtype=np.uint8
    )
    named = [
        (f"{KV_META_PREFIX}{rid}", mjson),
        (f"{KV_DATA_PREFIX}{rid}/k", k),
        (f"{KV_DATA_PREFIX}{rid}/v", v),
    ]
    if ks is not None:
        named.append((f"{KV_DATA_PREFIX}{rid}/ks", ks))
        named.append((f"{KV_DATA_PREFIX}{rid}/vs", vs))
    yield from pack_buckets(named, chunk_mb=chunk_mb)


def unpack_kv_sessions(
    staged: dict[str, np.ndarray],
) -> list[tuple[dict, np.ndarray, np.ndarray, tuple[np.ndarray, np.ndarray] | None]]:
    """Finalized staging → [(meta, k, v, scales)] per complete session,
    where `scales` is (ks, vs) for int8 sessions and None for fp ones.

    Raises ValueError when a session is structurally incomplete (metadata
    without blocks, an int8 session missing its scale blocks, or vice
    versa) or its metadata is malformed — the commit handler turns that
    into a client-visible error instead of importing a half-session."""
    out: list[
        tuple[dict, np.ndarray, np.ndarray, tuple[np.ndarray, np.ndarray] | None]
    ] = []
    meta_keys = sorted(n for n in staged if n.startswith(KV_META_PREFIX))
    data_keys = {n for n in staged if n.startswith(KV_DATA_PREFIX)}
    for mk in meta_keys:
        rid = mk[len(KV_META_PREFIX):]
        meta = json.loads(np.asarray(staged[mk], dtype=np.uint8).tobytes())
        missing = [f for f in _KV_META_REQUIRED if f not in meta]
        if missing or str(meta["rid"]) != rid:
            raise ValueError(f"kv session {rid!r} metadata malformed")
        if meta.get("meta_only"):
            # cheap-drain session: metadata IS the whole payload
            out.append((meta, None, None, None))
            continue
        kk = f"{KV_DATA_PREFIX}{rid}/k"
        vk = f"{KV_DATA_PREFIX}{rid}/v"
        if kk not in staged or vk not in staged:
            raise ValueError(f"kv session {rid!r} incomplete: missing blocks")
        sk = f"{KV_DATA_PREFIX}{rid}/ks"
        sv = f"{KV_DATA_PREFIX}{rid}/vs"
        scales = None
        if str(meta.get("kv_dtype", "fp")) == "int8":
            if sk not in staged or sv not in staged:
                raise ValueError(
                    f"kv session {rid!r} incomplete: int8 blocks without "
                    "scale blocks"
                )
            scales = (staged[sk], staged[sv])
            data_keys.discard(sk)
            data_keys.discard(sv)
        out.append((meta, staged[kk], staged[vk], scales))
        data_keys.discard(kk)
        data_keys.discard(vk)
    if data_keys:
        raise ValueError(
            f"kv blocks without session metadata: {sorted(data_keys)[:4]}"
        )
    return out
