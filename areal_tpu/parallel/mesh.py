"""Device mesh construction: ParallelStrategy → jax.sharding.Mesh.

This is the TPU replacement for the reference's process-group plumbing
(realhf/base/topology.py ProcessTopology/ParallelGrid, areal/utils/fsdp/
parallel.py ParallelHelper.world_mesh): one named mesh, and every
parallelism dimension becomes sharding annotations over its axes. XLA then
inserts the collectives (psum/all-gather/reduce-scatter/all-to-all) that the
reference issues by hand through NCCL.

Axis layout (order matters — later axes vary fastest, i.e. are nearest
neighbours on the ICI torus):

    ("pp", "dp", "sp", "tp")

- "tp"  innermost: tensor-parallel collectives (per-layer all-reduce /
  reduce-scatter) are the most latency-sensitive → adjacent chips.
- "sp"  context/sequence parallelism (ring attention all-to-alls).
- "dp"  data parallel; parameters are additionally sharded over this axis
  ZeRO-3-style when fsdp is enabled (the reference's FSDP2 dim).
- "pp"  outermost: pipeline stages communicate least often.

Expert parallelism folds over ("dp", "sp") — the reference likewise carves
EP out of the dp×cp ranks (Megatron MoE parallel folding,
areal/api/alloc_mode.py expert_data_parallel_size).
"""

from __future__ import annotations

import contextlib
import threading

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from areal_tpu.api.alloc_mode import ParallelStrategy

AXIS_PP = "pp"
AXIS_DP = "dp"
AXIS_SP = "sp"
AXIS_TP = "tp"
MESH_AXES = (AXIS_PP, AXIS_DP, AXIS_SP, AXIS_TP)

# Ambient mesh: engines register their mesh here so ops deep inside the
# jitted model (ring attention's shard_map) can reach it without threading a
# Mesh through every pure function signature.
_CURRENT_MESH: Mesh | None = None

# Per-thread override. Two engines with DIFFERENT topologies can share a
# process (COLOCATE: the train engine's 8-chip mesh + a tp-sharded decode
# engine over a subset), each running compute on its own thread. A traced
# `constrain` must resolve the mesh of the engine whose thread is tracing,
# never the other engine's — a constraint naming devices the operand doesn't
# live on is a compile error. An entry may be None: that is an explicit
# "trace with no ambient mesh" binding (unsharded decode engine), distinct
# from an empty stack (fall through to the process-global).
_TLS = threading.local()


def set_current_mesh(mesh: Mesh | None) -> None:
    global _CURRENT_MESH
    _CURRENT_MESH = mesh


@contextlib.contextmanager
def mesh_scope(mesh: Mesh | None):
    """Bind the ambient mesh for the current thread (None = no mesh)."""
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    stack.append(mesh)
    try:
        yield
    finally:
        stack.pop()


def current_mesh() -> Mesh | None:
    stack = getattr(_TLS, "stack", None)
    if stack:
        return stack[-1]
    return _CURRENT_MESH


def clear_current_mesh_if(mesh: Mesh) -> None:
    """Unset the process-global ambient mesh iff it is `mesh` (engine
    teardown hygiene — never clobbers a mesh some other engine installed)."""
    global _CURRENT_MESH
    if _CURRENT_MESH is mesh:
        _CURRENT_MESH = None


def build_mesh(
    strategy: ParallelStrategy, devices: list | None = None
) -> Mesh:
    """Build the named device mesh for a parallel strategy.

    `devices` defaults to all global devices; their count must equal the
    strategy's world size.
    """
    if devices is None:
        devices = jax.devices()
    shape = (
        strategy.pp_size,
        strategy.dp_size,
        strategy.cp_size,
        strategy.tp_size,
    )
    world = int(np.prod(shape))
    if len(devices) != world:
        raise ValueError(
            f"strategy world size {world} ({strategy}) != device count "
            f"{len(devices)}"
        )
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, MESH_AXES)


def build_hybrid_mesh(
    strategy: ParallelStrategy,
    *,
    num_slices: int,
    dcn_axes: tuple[str, ...] = (AXIS_PP,),
    devices: list | None = None,
) -> Mesh:
    """Hybrid ICI/DCN mesh across `num_slices` accelerator slices.

    Multi-pod TPU topologies have two interconnects: the per-slice ICI
    torus and the much slower data-center network (DCN) between slices.
    A mesh axis placed across the slice boundary pays DCN latency for its
    collectives, so only the least-chatty axes belong there: "pp" (one
    stage-boundary activation hop per microbatch per round) and, for very
    large fleets, an outer "dp" split (one gradient reduce per step).
    Everything else keeps its ICI adjacency — the axis order *inside* a
    slice is unchanged from `build_mesh`.

    Each axis named in `dcn_axes` (in order) absorbs a factor of
    `num_slices`: its mesh dimension splits into (dcn_factor ×
    within-slice), with the slice coordinate varying slowest, exactly the
    convention of `jax.experimental.mesh_utils.create_hybrid_device_mesh`.
    That helper is used verbatim when the runtime exposes per-device
    `slice_index` (real multi-slice TPU); otherwise — CPU test fixtures,
    `--plan-check` on a dev box — the same device layout is emulated by
    treating consecutive device granules as slices, which produces an
    identically-shaped program for AOT compilation.
    """
    if devices is None:
        devices = jax.devices()
    shape = (
        strategy.pp_size,
        strategy.dp_size,
        strategy.cp_size,
        strategy.tp_size,
    )
    world = int(np.prod(shape))
    if len(devices) != world:
        raise ValueError(
            f"strategy world size {world} ({strategy}) != device count "
            f"{len(devices)}"
        )
    if num_slices <= 1:
        return build_mesh(strategy, devices)
    if world % num_slices:
        raise ValueError(
            f"world size {world} not divisible by num_slices={num_slices}"
        )
    import math

    dcn = [1] * len(MESH_AXES)
    remaining = num_slices
    for name in dcn_axes:
        if name not in MESH_AXES:
            raise ValueError(f"unknown dcn axis {name!r}; mesh axes are "
                             f"{MESH_AXES}")
        i = MESH_AXES.index(name)
        f = math.gcd(shape[i], remaining)
        dcn[i] = f
        remaining //= f
    if remaining != 1:
        raise ValueError(
            f"cannot factor num_slices={num_slices} over dcn_axes="
            f"{tuple(dcn_axes)} of mesh shape {shape}: {remaining} left over"
        )
    ici = tuple(n // d for n, d in zip(shape, dcn))
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    if None not in slice_ids and len(slice_ids) == num_slices:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici, tuple(dcn), devices=devices
        )
        return Mesh(dev_array, MESH_AXES)
    # Faked multi-slice topology: consecutive granules of world/num_slices
    # devices stand in for slices. Granules fill the DCN grid in C order,
    # devices inside a granule fill the ICI grid; interleaving the two
    # grids per axis (dcn coordinate slowest) reproduces the hybrid
    # layout create_hybrid_device_mesh would build.
    arr = np.asarray(devices).reshape(tuple(dcn) + ici)
    k = len(MESH_AXES)
    order = [x for i in range(k) for x in (i, k + i)]
    return Mesh(arr.transpose(order).reshape(shape), MESH_AXES)


def strategy_from_mesh(mesh: Mesh) -> ParallelStrategy:
    """Inverse of build_mesh (for logging / validation)."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    return ParallelStrategy(
        pipeline_parallel_size=sizes.get(AXIS_PP, 1),
        data_parallel_size=sizes.get(AXIS_DP, 1),
        context_parallel_size=sizes.get(AXIS_SP, 1),
        tensor_parallel_size=sizes.get(AXIS_TP, 1),
    )


# ---------------------------------------------------------------------------
# Logical-axis sharding rules (t5x/maxtext convention): model code annotates
# parameters/activations with *logical* axis names; these rules map them to
# mesh axes. Changing the parallel layout = changing this table, not the
# model. This one table subsumes the reference's DTensor TP plan
# (areal/utils/fsdp/parallel.py:255-396), Megatron Column/RowParallelLinear
# (realhf/impl/model/parallelism/tensor_parallel/modules.py), and Ulysses
# sequence sharding (areal/utils/ulysses.py).
# ---------------------------------------------------------------------------

LogicalRules = tuple[tuple[str, str | tuple[str, ...] | None], ...]

# fsdp=True: shard params' largest logical dims over the dp axis (ZeRO-3).
# pp=True: shard the scanned layer stack over the "pp" axis — each pipeline
# stage holds L/pp layers; the engine routes compute through
# parallel/pipeline.py's GPipe shard_map (forward_pipelined) so stages
# execute their own layers instead of gathering the full stack.
def default_rules(fsdp: bool = True, pp: bool = False) -> LogicalRules:
    fsdp_axis = AXIS_DP if fsdp else None
    return (
        # activations
        ("batch", AXIS_DP),
        ("seq", AXIS_SP),
        ("tokens", (AXIS_DP, AXIS_SP)),  # packed 1-D token streams
        # pipeline: the leading stage dim of stage-stacked activations /
        # layer stacks ([pp, ...] arrays inside parallel/pipeline.py)
        ("stages", AXIS_PP),
        ("act_embed", None),
        ("act_heads", AXIS_TP),
        ("act_kv_heads", AXIS_TP),
        ("act_mlp", AXIS_TP),
        ("act_vocab", AXIS_TP),
        # parameters
        ("vocab", AXIS_TP),
        ("embed", fsdp_axis),
        ("heads", AXIS_TP),
        ("kv_heads", AXIS_TP),
        ("head_dim", None),
        ("mlp", AXIS_TP),
        ("experts", AXIS_DP),  # EP folds over dp ranks
        ("layers", AXIS_PP if pp else None),
        ("norm", None),
    )


def logical_to_mesh_axes(
    logical_axes: tuple[str | None, ...], rules: LogicalRules
) -> PartitionSpec:
    """Map a tuple of logical axis names to a PartitionSpec via `rules`."""
    table = dict(rules)
    out = []
    used: set[str] = set()
    for name in logical_axes:
        if name is None:
            out.append(None)
            continue
        axis = table.get(name)
        # A mesh axis may shard at most one dim of a given array.
        if axis is not None and axis in used:
            axis = None
        if axis is not None:
            used.add(axis) if isinstance(axis, str) else used.update(axis)
        out.append(axis)
    return PartitionSpec(*out)


def named_sharding(
    mesh: Mesh, logical_axes: tuple[str | None, ...], rules: LogicalRules | None = None
) -> NamedSharding:
    rules = rules if rules is not None else default_rules()
    return NamedSharding(mesh, logical_to_mesh_axes(logical_axes, rules))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for [B, T, ...] batches: batch over dp, sequence over sp."""
    return NamedSharding(mesh, PartitionSpec(AXIS_DP, AXIS_SP))


def packed_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for packed 1-D token streams: tokens over (dp, sp)."""
    return NamedSharding(mesh, PartitionSpec((AXIS_DP, AXIS_SP)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def constrain(
    x: jax.Array, *logical_axes: str | None, mesh: Mesh | None = None
) -> jax.Array:
    """Pin an activation's layout by logical axis names (no-op without an
    ambient mesh).

    Model code calls this at layer boundaries so GSPMD's propagation never
    has to *guess* activation layouts — an unconstrained backward pass is
    where "involuntary full rematerialization" reshards come from: XLA
    derives one layout for a scan residual from the forward and a different
    one from the gradient flow, then replicates to bridge them.

    `mesh` overrides the ambient mesh — parallel/pipeline.py pins its
    stage-stacked carries against the engine mesh while the stage bodies
    trace under mesh_scope(None).
    """
    if mesh is None:
        mesh = current_mesh()
    if mesh is None:
        return x
    spec = logical_to_mesh_axes(logical_axes, default_rules())
    # A logical axis mapping to no mesh axis is deliberately PINNED
    # replicated (None) — that is the layout statement. But a mesh axis that
    # doesn't divide the dim (tiny test shapes) becomes UNCONSTRAINED —
    # "let GSPMD choose" — because pinning replicated there would force an
    # all-gather the caller never asked for.
    fixed = []
    for dim, axes in zip(x.shape, spec):
        if axes is None:
            fixed.append(None)
            continue
        group = (axes,) if isinstance(axes, str) else tuple(axes)
        size = 1
        for a in group:
            size *= mesh.shape.get(a, 1)
        fixed.append(axes if dim % size == 0 else PartitionSpec.UNCONSTRAINED)
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, PartitionSpec(*fixed))
    )


def manual_shard_map(f, mesh: Mesh, in_specs, out_specs):
    """Fully-manual shard_map across jax API generations.

    jax >= 0.6 exposes `jax.shard_map` (with `check_vma`); older releases
    (this container ships 0.4.x) only have
    `jax.experimental.shard_map.shard_map` (with `check_rep`). Both are the
    same primitive for the fully-manual case ring attention needs — every
    mesh axis manual, replication checking off.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
    )
