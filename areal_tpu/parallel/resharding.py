"""General parameter resharding between parallel topologies.

Parity: the reference's param-realloc subsystem — live re-sharding of
weights between disjoint train/gen topologies (realhf/impl/model/comm/
param_realloc.py:157,351: pairwise rank comm plans of Reparallelize
Sender/ReceiverSteps executed as NCCL broadcasts, plus the flat-buffer
interval copy kernels in csrc/interval_op). On TPU the ENTIRE subsystem
collapses into `jax.device_put` with the target NamedShardings: XLA's
runtime computes the minimal device-to-device transfer plan (the comm plan
derivation, the interval math, and the collectives are all the compiler/
runtime's job). This module is the explicit utility + the η-mixing the
legacy hook applied (dfg.py:29: target = η·src + (1-η)·target).
"""

from __future__ import annotations

from typing import Any

import jax

from areal_tpu.api.alloc_mode import ParallelStrategy
from areal_tpu.parallel import mesh as mesh_lib


def shardings_for(
    strategy: ParallelStrategy,
    model_config,
    *,
    devices: list | None = None,
    fsdp: bool = True,
):
    """(mesh, param shardings) for a strategy — the target topology."""
    from areal_tpu.models.qwen2 import param_logical_axes

    mesh = mesh_lib.build_mesh(strategy, devices)
    pp = strategy.pp_size > 1
    rules = mesh_lib.default_rules(fsdp=fsdp, pp=pp)
    axes = param_logical_axes(model_config)
    shardings = jax.tree.map(
        lambda a: mesh_lib.named_sharding(mesh, a, rules),
        axes,
        is_leaf=lambda x: isinstance(x, tuple),
    )
    return mesh, shardings


def reshard(params: Any, target_shardings: Any) -> Any:
    """Move a param tree onto new shardings (possibly a different mesh /
    device set). One call = the whole legacy comm plan."""
    return jax.tree.map(jax.device_put, params, target_shardings)


def reshard_to_strategy(
    params: Any,
    strategy: ParallelStrategy,
    model_config,
    *,
    devices: list | None = None,
    fsdp: bool = True,
):
    """Reshard onto a strategy's canonical layout; returns
    (params, mesh, shardings)."""
    mesh, shardings = shardings_for(
        strategy, model_config, devices=devices, fsdp=fsdp
    )
    return reshard(params, shardings), mesh, shardings


@jax.jit
def _mix(t: Any, s: Any, eta: jax.Array) -> Any:
    # module-level jit: the per-weight-push mixing hook must hit the
    # compile cache, not re-trace a fresh closure every update
    return jax.tree.map(
        lambda a, b: (eta * b.astype(a.dtype) + (1.0 - eta) * a).astype(
            a.dtype
        ),
        t,
        s,
    )


def eta_mix(target: Any, src: Any, eta: float) -> Any:
    """target <- eta * src + (1 - eta) * target (the legacy realloc hook's
    mixing rule, realhf/api/core/dfg.py:29), computed on the TARGET's
    layout — src reshards onto it first."""
    src_on_target = reshard(src, jax.tree.map(lambda x: x.sharding, target))
    if eta >= 1.0:
        return src_on_target
    import jax.numpy as jnp

    return _mix(target, src_on_target, jnp.float32(eta))
