"""GPipe-style SPMD pipeline parallelism over the "pp" mesh axis.

Parity target: the reference's native pipeline engine —
realhf/impl/model/parallelism/pipeline_parallel/static_schedule.py:159
(instruction schedules) + pipe_runner.py:778 (executors) and Megatron's
forward_backward_func (areal/engine/megatron_engine.py:846). The TPU
re-design replaces instruction lists + p2p send/recv with a single jitted
program: a `jax.shard_map` manual over the "pp" axis (auto over dp/sp/tp,
so GSPMD still handles FSDP/TP/SP inside each stage) where

- the stacked layer parameters [L, ...] are sharded over pp on dim 0, so
  each stage holds L/pp layers (the memory scaling PP exists for),
- M microbatches stream through the stages: at step t, stage s runs
  microbatch (t - s); activations hop stage→stage with one
  `lax.ppermute` per step (the ICI analogue of Megatron's p2p),
- the loop runs M + pp - 1 steps (fill + drain), outputs are collected on
  the last stage and replicated with one masked psum.

Autodiff runs straight through (ppermute transposes to the reverse
permutation), which yields the backward pipeline automatically — no 1F1B
instruction table. XLA overlaps the ppermute with the next step's compute
where the schedule allows.

Attention inside a stage must not itself shard tokens over (dp, sp) with a
kernel that can't be partitioned (ring attention's own shard_map does not
nest inside the pp-manual region); the model resolves attention to a
pp-compatible impl while tracing the stage body (see forward_pipelined).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from areal_tpu.parallel import mesh as mesh_lib


def pipeline_trunk(
    mesh: Mesh,
    stage_fn: Callable[[Any, jax.Array, Any], tuple[jax.Array, jax.Array]],
    layers: Any,
    xs: jax.Array,
    aux_inputs: Any,
) -> tuple[jax.Array, jax.Array]:
    """Run `stage_fn` over pp stages for M microbatches.

    Args:
      mesh: the engine mesh; must contain a "pp" axis of size >= 2.
      stage_fn: (layers_local, x, aux) -> (y, scalar_aux_loss); sees the
        stage-local [L/pp, ...] layer stack and one microbatch activation.
      layers: stacked [L, ...] pytree (sharded over pp on dim 0 by the
        engine's param shardings).
      xs: [M, T, H] stacked microbatch activations.
      aux_inputs: pytree of [M, ...] per-microbatch side inputs (positions,
        segment ids, ...) indexed — not circulated — per step.

    Returns (ys [M, T, H], total_aux_loss), both replicated over pp.
    """
    pp = mesh.shape[mesh_lib.AXIS_PP]
    M = xs.shape[0]
    steps = M + pp - 1
    perm = [(i, (i + 1) % pp) for i in range(pp)]

    def staged(layers_local, xs, aux_inputs):
        stage = jax.lax.axis_index(mesh_lib.AXIS_PP)

        def step(carry, t):
            state, outbuf, aux_sum = carry
            # stage s works on microbatch m = t - s (valid when 0 <= m < M)
            m = jnp.clip(t - stage, 0, M - 1)
            fresh = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, M - 1), 0, keepdims=False
            )
            x_in = jnp.where(stage == 0, fresh, state)
            aux_t = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(a, m, 0, keepdims=False),
                aux_inputs,
            )
            y, aux = stage_fn(layers_local, x_in, aux_t)
            valid = (t - stage >= 0) & (t - stage < M)
            aux_sum = aux_sum + jnp.where(valid, aux, 0.0)
            out_m = jnp.clip(t - (pp - 1), 0, M - 1)
            is_out = (stage == pp - 1) & (t >= pp - 1)
            prev_row = jax.lax.dynamic_index_in_dim(
                outbuf, out_m, 0, keepdims=False
            )
            outbuf = jax.lax.dynamic_update_index_in_dim(
                outbuf,
                jnp.where(is_out, y, prev_row).astype(outbuf.dtype),
                out_m,
                0,
            )
            state = jax.lax.ppermute(y, mesh_lib.AXIS_PP, perm)
            return (state, outbuf, aux_sum), None

        init = (
            jnp.zeros_like(xs[0]),
            jnp.zeros_like(xs),
            jnp.float32(0.0),
        )
        (_, outbuf, aux_sum), _ = jax.lax.scan(
            step, init, jnp.arange(steps)
        )
        # Only the last stage's buffer holds real outputs; a masked psum
        # replicates it across pp (one collective per step, not per token).
        outbuf = jax.lax.psum(
            jnp.where(stage == pp - 1, outbuf, jnp.zeros_like(outbuf)),
            mesh_lib.AXIS_PP,
        )
        aux_sum = jax.lax.psum(aux_sum, mesh_lib.AXIS_PP)
        return outbuf, aux_sum

    return jax.shard_map(
        staged,
        mesh=mesh,
        in_specs=(P(mesh_lib.AXIS_PP), P(), P()),
        out_specs=(P(), P()),
        axis_names=frozenset({mesh_lib.AXIS_PP}),
        check_vma=False,
    )(layers, xs, aux_inputs)
