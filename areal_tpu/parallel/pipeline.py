"""SPMD pipeline parallelism over the "pp" mesh axis — stage-stacked GSPMD.

Parity target: the reference's native pipeline engine —
realhf/impl/model/parallelism/pipeline_parallel/static_schedule.py:159
(instruction schedules) + pipe_runner.py:778 (executors) and Megatron's
forward_backward_func (areal/engine/megatron_engine.py:846). The TPU
re-design replaces instruction lists + p2p send/recv with a single jitted
program over *stage-stacked* arrays:

- the stacked layer parameters [L, ...] are reshaped to [pp, L/pp, ...] and
  sharded over the "pp" mesh axis on dim 0, so each stage holds L/pp layers
  (the memory scaling PP exists for),
- pipeline state is [pp, T, H]: row s is the activation stage s works on.
  One `jax.vmap(stage_fn)` over the leading dim runs every stage in
  parallel — GSPMD partitions the vmapped program over "pp" (and keeps
  handling dp/sp/tp automatically inside each stage),
- activations hop stage→stage with `jnp.roll(y, 1, axis=0)` — a static
  rotation XLA lowers to the same neighbour collective-permute a manual
  ppermute would emit. (An earlier revision used a partial-manual
  `shard_map` with explicit ppermutes; the stage-stacked form is
  numerically identical, and — unlike partial-auto shard_map — also
  compiles on the 0.4.x jax this repo must still run on.)

Three schedules:

- `pipeline_trunk` — GPipe: all M forwards stream through (M + pp - 1
  steps), outputs collect on the last stage, autodiff runs straight back
  through the scan. Simple and the numerics reference, but the backward
  scan holds residuals for every step, so live activation memory grows
  with M.
- `pipeline_1f1b_grads` — 1F1B: one interleaved loop of M + 2·pp - 2
  rounds where every round runs one forward AND one backward per stage
  (warmup/cooldown rounds masked). The backward is explicit — a per-stage
  `jax.vjp` that recomputes the stage forward from a stashed input — so
  nothing autodiffs through the round scan and the live stash is capped at
  2·pp - 1 stage inputs per stage regardless of M. Microbatch m's loss
  gradient is seeded in the same round its forward reaches the last stage
  (head + loss + vjp run inline on that stage's output), which is what
  lets the stash recycle. Larger M therefore fits in fixed HBM and the
  bubble fraction (pp-1)/(M+pp-1) shrinks at fixed memory — the point of
  1F1B (GPipe stays available via `pipeline_schedule: gpipe`).

- `pipeline_1f1b_interleaved_grads` — interleaved 1F1B (Megatron's
  virtual-pipeline schedule, arXiv:2104.04473): each pp rank holds v
  NON-contiguous chunks of L/(pp·v) layers (chunk c = vc·pp + s lives on
  rank s), so a microbatch hops rank 0→1→...→pp-1 v times. The warmup /
  cooldown bubble shrinks ~1/v because a rank starts chunk vc=0 of the
  next microbatch group while deeper chunks are still in flight, at the
  cost of v× more (but v× smaller) stage hops. The stash stays bounded:
  per-chunk capacities are computed statically from the timetable and sum
  to at most v·(2·pp - 1) live microbatch activations per rank.

Schedule timetable (round r, stage s, microbatch m, P = pp):
    F(m, s) at r = m + s              (forward wavefront, GPipe-like)
    B(m, s) at r = m + 2P - 2 - s     (backward wavefront, mirrored)
so F(m, P-1) and B(m, P-1) land in the SAME round (loss seeds backward
immediately) and stage s holds at most 2(P-1-s)+1 <= 2P-1 stashed inputs.

Interleaved timetable (v chunks per rank, chunk c = vc·P + s, microbatch
m = g·P + u with u = m % P, Δ = v·P - 1):
    F(m, c) at r = g·v·P + vc·P + u + s
    B(m, c) at r = Δ + g·v·P + (v-1-vc)·P + u + (P-1-s)
Both hops stay the uniform neighbour rotation (roll ±1): finishing chunk c
on rank P-1 wraps to chunk c+1 on rank 0 exactly one round later. At v=1
this reduces term-for-term to the plain 1F1B table above. F(m, C-1) and
B(m, C-1) land in the same round, so the loss seeds the backward
immediately and the stash recycles. A round is decoded per rank from
n = r - s (forward) and n = r - Δ - (P-1-s) (backward) as mixed-radix
(g, vc, u) digits — at most one forward and one backward chunk per rank
per round, like plain 1F1B.

The interleaved schedule expects the engine to store the stacked layer
parameters in CHUNK-MAJOR order (see `interleave_layer_indices`): storage
slot p = s·(v·Lc) + vc·Lc + i holds model layer (vc·P + s)·Lc + i, so the
[L, ...] → [P, v, Lc, ...] reshape is a pure metadata operation and the
pp-sharded leading dim stays contiguous — no layer ever moves between
ranks at dispatch time.

Attention inside a stage must not itself shard tokens over (dp, sp) with a
kernel that can't be partitioned (ring attention's shard_map cannot nest
under the stage vmap); the model resolves attention to a pp-compatible impl
while tracing the stage body (see qwen2.forward_pipelined).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from areal_tpu.parallel import mesh as mesh_lib

# Engine-facing names for the trunk schedules (api/cli_args.py
# JaxEngineConfig.pipeline_schedule).
PIPELINE_SCHEDULES = ("gpipe", "1f1b", "1f1b_interleaved")


def interleave_layer_indices(L: int, pp: int, v: int) -> list[int]:
    """Model-layer index stored at each engine slot under the interleaved
    layout: slot p = s·(v·Lc) + vc·Lc + i holds model layer (vc·pp+s)·Lc + i
    (Lc = L/(pp·v)), so reshaping the engine stack [L] → [pp, v, Lc] lands
    chunk c = vc·pp + s at [s, vc] with the pp-sharded dim contiguous.

    At v=1 this is the identity — plain 1F1B's contiguous split."""
    assert L % (pp * v) == 0, (L, pp, v)
    Lc = L // (pp * v)
    return [
        (vc * pp + s) * Lc + i
        for s in range(pp)
        for vc in range(v)
        for i in range(Lc)
    ]


def inverse_interleave_layer_indices(L: int, pp: int, v: int) -> list[int]:
    """Engine slot holding each model layer (inverse permutation — used to
    restore model order on export/save)."""
    perm = interleave_layer_indices(L, pp, v)
    inv = [0] * L
    for p, model_l in enumerate(perm):
        inv[model_l] = p
    return inv


def _chunk_stack(layers: Any, pp: int, v: int) -> Any:
    """[L, ...] chunk-major layer pytree → [pp, v, L/(pp·v), ...]."""

    def split(leaf):
        L = leaf.shape[0]
        assert L % (pp * v) == 0, (L, pp, v)
        return leaf.reshape(pp, v, L // (pp * v), *leaf.shape[1:])

    return jax.tree.map(split, layers)


def _pick_chunk(tree_rank: Any, vc: jax.Array) -> Any:
    """Select chunk vc out of a rank-local [v, Lc, ...] pytree (vmapped over
    the pp dim by callers, so vc may differ per rank)."""
    return jax.tree.map(
        lambda a: jax.lax.dynamic_index_in_dim(a, vc, 0, keepdims=False),
        tree_rank,
    )


def _interleaved_stash_sizes(pp: int, v: int, M: int) -> list[int]:
    """Per-virtual-chunk stash capacity: the max number of microbatches
    simultaneously live (forward stashed, backward not yet consumed —
    window [r_F, r_B] inclusive) for chunk position vc, maxed over ranks.

    The live set at any round is a consecutive microbatch interval (r_F and
    r_B are both strictly increasing in m), so slot = m % size is
    collision-free. Sizes sum to <= v·(2·pp - 1)."""
    delta = v * pp - 1
    sizes = []
    for vc in range(v):
        best = 1
        for s in range(pp):
            rf, rb = [], []
            for m in range(M):
                g, u = divmod(m, pp)
                rf.append(g * v * pp + vc * pp + u + s)
                rb.append(
                    delta + g * v * pp + (v - 1 - vc) * pp + u + (pp - 1 - s)
                )
            lo = 0
            for m in range(M):
                while rb[lo] < rf[m]:
                    lo += 1
                best = max(best, m - lo + 1)
        sizes.append(best)
    return sizes


def _stage_stack(layers: Any, pp: int) -> Any:
    """[L, ...] stacked layer pytree → [pp, L/pp, ...].

    The reshape splits the pp-sharded leading dim on its sharded factor, so
    GSPMD keeps each stage's L/pp layers on its own shard — no data moves.
    """

    def split(leaf):
        L = leaf.shape[0]
        assert L % pp == 0, (L, pp)
        return leaf.reshape(pp, L // pp, *leaf.shape[1:])

    return jax.tree.map(split, layers)


def _index_mb(tree: Any, m: jax.Array) -> Any:
    """Slice the m-th microbatch out of a pytree of [M, ...] arrays."""
    return jax.tree.map(
        lambda a: jax.lax.dynamic_index_in_dim(a, m, 0, keepdims=False), tree
    )


def _gather_per_stage(tree: Any, m_per_stage: jax.Array) -> Any:
    """Per-stage microbatch selection: tree of [M, ...] → tree of [pp, ...]
    where row s is the m_per_stage[s]-th microbatch."""
    return jax.vmap(lambda m: _index_mb(tree, m))(m_per_stage)


def _masked_row_write(
    buf: jax.Array, val: jax.Array, idx: jax.Array, valid: jax.Array
) -> jax.Array:
    """buf[idx] = val where valid, else keep — the write-or-keep idiom that
    makes clipped (out-of-schedule) indices harmless."""
    prev = jax.lax.dynamic_index_in_dim(buf, idx, 0, keepdims=False)
    return jax.lax.dynamic_update_index_in_dim(
        buf, jnp.where(valid, val, prev).astype(buf.dtype), idx, 0
    )


def _pin_stagewise(
    mesh: Mesh, x: jax.Array, token_dim: int = 1
) -> jax.Array:
    """Pin a stage-stacked pipeline carry: dim 0 over "pp", `token_dim`
    over (dp, sp); remaining dims pinned replicated."""
    axes: list[str | None] = [None] * x.ndim
    axes[0] = "stages"
    axes[token_dim] = "tokens"
    return mesh_lib.constrain(x, *axes, mesh=mesh)


def pipeline_trunk(
    mesh: Mesh,
    stage_fn: Callable[[Any, jax.Array, Any], tuple[jax.Array, jax.Array]],
    layers: Any,
    xs: jax.Array,
    aux_inputs: Any,
    *,
    virtual: int = 1,
) -> tuple[jax.Array, jax.Array]:
    """GPipe schedule: run `stage_fn` over pp stages for M microbatches.

    Args:
      mesh: the engine mesh; must contain a "pp" axis of size >= 2.
      stage_fn: (layers_local, x, aux) -> (y, scalar_aux_loss); sees the
        stage-local [L/pp, ...] layer stack and one microbatch activation.
      layers: stacked [L, ...] pytree (sharded over pp on dim 0 by the
        engine's param shardings). With virtual > 1 the stack must be in
        the chunk-major interleaved layout (`interleave_layer_indices`).
      xs: [M, T, H] stacked microbatch activations.
      aux_inputs: pytree of [M, ...] per-microbatch side inputs (positions,
        segment ids, ...) indexed — not circulated — per step.
      virtual: virtual stages per rank; > 1 runs the interleaved forward
        wavefront (each rank cycles through its v chunks).

    Returns (ys [M, T, H], total_aux_loss). Autodiff runs straight through
    (the backward pipeline falls out of the scan's reverse), which is the
    reference path `pipeline_1f1b_grads` is validated against.
    """
    pp = mesh.shape[mesh_lib.AXIS_PP]
    M = xs.shape[0]
    if virtual > 1:
        return _trunk_interleaved(
            mesh, stage_fn, layers, xs, aux_inputs, virtual=virtual
        )
    steps = M + pp - 1
    stages = jnp.arange(pp)
    layers_s = _stage_stack(layers, pp)

    def step(carry, t):
        state, outbuf, aux_sum = carry
        # stage s works on microbatch m = t - s (valid when 0 <= m < M)
        mf = t - stages
        f_valid = (mf >= 0) & (mf < M)
        mf_c = jnp.clip(mf, 0, M - 1)
        fresh = jax.lax.dynamic_index_in_dim(
            xs, jnp.clip(t, 0, M - 1), 0, keepdims=False
        )
        x_in = jnp.where((stages == 0)[:, None, None], fresh[None], state)
        y, aux = jax.vmap(stage_fn)(
            layers_s, x_in, _gather_per_stage(aux_inputs, mf_c)
        )
        aux_sum = aux_sum + jnp.sum(jnp.where(f_valid, aux, 0.0))
        # the last stage finishes microbatch t - (pp - 1)
        out_m = jnp.clip(t - (pp - 1), 0, M - 1)
        outbuf = _masked_row_write(outbuf, y[pp - 1], out_m, t >= pp - 1)
        state = _pin_stagewise(mesh, jnp.roll(y, 1, axis=0))
        return (state, outbuf, aux_sum), None

    init = (
        _pin_stagewise(mesh, jnp.zeros((pp,) + xs.shape[1:], xs.dtype)),
        jnp.zeros_like(xs),
        jnp.float32(0.0),
    )
    (_, outbuf, aux_sum), _ = jax.lax.scan(step, init, jnp.arange(steps))
    return outbuf, aux_sum


def _fwd_decode(r, stages, pp, v, M):
    """Mixed-radix forward decode: which (chunk, microbatch) each rank runs
    at round r. n = r - s = g·v·pp + vc·pp + u with m = g·pp + u."""
    n = r - stages
    u = n % pp
    vc = (n // pp) % v
    m = (n // (pp * v)) * pp + u
    valid = (n >= 0) & (m < M)
    return vc, m, jnp.clip(m, 0, M - 1), valid


def _trunk_interleaved(mesh, stage_fn, layers, xs, aux_inputs, *, virtual):
    """Forward-only interleaved wavefront (autodiff-through, GPipe-style
    memory): rank s runs chunk vc = (n//pp) % v of microbatch m at round
    r = n + s, n = g·v·pp + vc·pp + u."""
    pp = mesh.shape[mesh_lib.AXIS_PP]
    v = int(virtual)
    M = xs.shape[0]
    steps = ((M - 1) // pp) * v * pp + (v - 1) * pp + (M - 1) % pp + pp
    stages = jnp.arange(pp)
    layers_c = _chunk_stack(layers, pp, v)

    def step(carry, t):
        state, outbuf, aux_sum = carry
        vcf, _, mf_c, f_valid = _fwd_decode(t, stages, pp, v, M)
        fresh = jax.lax.dynamic_index_in_dim(xs, mf_c[0], 0, keepdims=False)
        entry = (stages == 0) & (vcf == 0)
        x_in = jnp.where(entry[:, None, None], fresh[None], state)
        y, aux = jax.vmap(stage_fn)(
            jax.vmap(_pick_chunk)(layers_c, vcf),
            x_in,
            _gather_per_stage(aux_inputs, mf_c),
        )
        aux_sum = aux_sum + jnp.sum(jnp.where(f_valid, aux, 0.0))
        # the last rank finishing its LAST chunk completes microbatch m
        out_valid = f_valid[pp - 1] & (vcf[pp - 1] == v - 1)
        outbuf = _masked_row_write(outbuf, y[pp - 1], mf_c[pp - 1], out_valid)
        state = _pin_stagewise(mesh, jnp.roll(y, 1, axis=0))
        return (state, outbuf, aux_sum), None

    init = (
        _pin_stagewise(mesh, jnp.zeros((pp,) + xs.shape[1:], xs.dtype)),
        jnp.zeros_like(xs),
        jnp.float32(0.0),
    )
    (_, outbuf, aux_sum), _ = jax.lax.scan(step, init, jnp.arange(steps))
    return outbuf, aux_sum


def pipeline_1f1b_grads(
    mesh: Mesh,
    stage_fn: Callable[[Any, jax.Array, Any], tuple[jax.Array, jax.Array]],
    head_loss_fn: Callable[[Any, jax.Array, Any], tuple[jax.Array, Any]],
    layers: Any,
    head_params: Any,
    xs: jax.Array,
    aux_inputs: Any,
    mb_data: Any,
    weights: jax.Array,
    *,
    aux_coef: float = 0.0,
) -> tuple[jax.Array, Any, jax.Array, Any, Any, jax.Array]:
    """1F1B schedule with the backward interleaved into the forward loop.

    This does NOT return a differentiable value — it returns the gradients
    themselves, computed by explicit per-stage `jax.vjp` (recompute from a
    stashed stage input, so the stage body is effectively rematerialised).
    Callers (models/qwen2.forward_pipelined_grads) compose these trunk
    gradients with the embedding / lora-combine / head-selection vjps.

    Args:
      stage_fn / layers / xs / aux_inputs: as `pipeline_trunk`.
      head_loss_fn: (head_params, y [T, H], mb_m) -> (scalar_loss, stats)
        — the final-norm + lm-head + caller loss for ONE microbatch, run on
        the last stage's output in the same round it is produced.
      head_params: pytree the head reads (final norm / lm head / tied
        embeddings ...), replicated over pp.
      mb_data: pytree of [M, ...] per-microbatch loss inputs.
      weights: [M] float32 loss weights; gradients equal
        d(sum_m weights[m]·loss_m + aux_coef·aux_total)/dθ.
      aux_coef: cotangent seeded into each stage's scalar aux output (MoE
        router load-balance coefficient; 0 when unused).

    Returns (losses [M], stats pytree of [M, ...], aux_total,
    g_layers [L, ...], g_head, g_xs [M, T, H]).
    """
    pp = mesh.shape[mesh_lib.AXIS_PP]
    M = xs.shape[0]
    S = 2 * pp - 1  # stash slots: max in-flight microbatches on stage 0
    rounds = M + 2 * pp - 2
    stages = jnp.arange(pp)
    layers_s = _stage_stack(layers, pp)

    # Probe the stats pytree structure so the [M]-buffers can be carried
    # through the scan (eval_shape only — nothing runs here).
    _, stats_shape = jax.eval_shape(
        head_loss_fn, head_params, jax.ShapeDtypeStruct(xs.shape[1:], xs.dtype),
        jax.eval_shape(lambda t: _index_mb(t, 0), mb_data),
    )

    def round_fn(carry, r):
        (fwd_in, bwd_in, stash, g_layers, g_head, dxs, losses, stats,
         aux_sum) = carry

        # ---- one forward per stage: F(m, s) at r = m + s ----------------
        mf = r - stages
        f_valid = (mf >= 0) & (mf < M)
        mf_c = jnp.clip(mf, 0, M - 1)
        fresh = jax.lax.dynamic_index_in_dim(
            xs, jnp.clip(r, 0, M - 1), 0, keepdims=False
        )
        x_in = jnp.where((stages == 0)[:, None, None], fresh[None], fwd_in)
        y, aux_f = jax.vmap(stage_fn)(
            layers_s, x_in, _gather_per_stage(aux_inputs, mf_c)
        )
        aux_sum = aux_sum + jnp.sum(jnp.where(f_valid, aux_f, 0.0))
        # Stash the stage INPUT (not output): the explicit backward re-runs
        # the stage from it. Invalid rounds must keep, not clobber — the
        # clipped slot may still be live.
        stash = jax.vmap(_masked_row_write)(stash, x_in, mf_c % S, f_valid)

        # ---- head + loss + seed on the last stage's fresh output --------
        m_last = r - (pp - 1)
        l_valid = (m_last >= 0) & (m_last < M)
        m_last_c = jnp.clip(m_last, 0, M - 1)
        mb_m = _index_mb(mb_data, m_last_c)
        w_m = jnp.where(
            l_valid,
            jax.lax.dynamic_index_in_dim(weights, m_last_c, 0, keepdims=False),
            0.0,
        )
        loss_m, head_vjp, stats_m = jax.vjp(
            lambda hp, y_: head_loss_fn(hp, y_, mb_m),
            head_params,
            y[pp - 1],
            has_aux=True,
        )
        # vjp is linear in the cotangent: a zero weight on out-of-schedule
        # rounds zeroes both the head grads and the backward seed.
        g_head_m, dy = head_vjp(jnp.zeros_like(loss_m) + w_m)
        g_head = jax.tree.map(jnp.add, g_head, g_head_m)
        losses = _masked_row_write(losses, loss_m, m_last_c, l_valid)
        stats = jax.tree.map(
            lambda b, v: _masked_row_write(b, v, m_last_c, l_valid),
            stats,
            stats_m,
        )

        # ---- one backward per stage: B(m, s) at r = m + 2pp - 2 - s -----
        mb_idx = r - (2 * pp - 2 - stages)
        b_valid = (mb_idx >= 0) & (mb_idx < M)
        mb_c = jnp.clip(mb_idx, 0, M - 1)
        g_in = jnp.where((stages == pp - 1)[:, None, None], dy[None], bwd_in)
        g_in = jnp.where(b_valid[:, None, None], g_in, 0.0)
        g_aux = jnp.where(b_valid, jnp.float32(aux_coef), 0.0)
        x_saved = jax.vmap(
            lambda st, slot: jax.lax.dynamic_index_in_dim(
                st, slot, 0, keepdims=False
            )
        )(stash, mb_c % S)
        aux_b = _gather_per_stage(aux_inputs, mb_c)

        def stage_bwd(layers_local, x, aux_t, gy, ga):
            _, vjp = jax.vjp(
                lambda L_, x_: stage_fn(L_, x_, aux_t), layers_local, x
            )
            return vjp((gy.astype(x.dtype), ga))

        g_layers_m, gx = jax.vmap(stage_bwd)(
            layers_s, x_saved, aux_b, g_in, g_aux
        )
        g_layers = jax.tree.map(jnp.add, g_layers, g_layers_m)
        # stage 0's input gradient feeds the embedding backward
        dxs = _masked_row_write(
            dxs, gx[0], jnp.clip(r - (2 * pp - 2), 0, M - 1), b_valid[0]
        )

        fwd_in = _pin_stagewise(mesh, jnp.roll(y, 1, axis=0))
        bwd_in = _pin_stagewise(mesh, jnp.roll(gx, -1, axis=0))
        return (
            (fwd_in, bwd_in, stash, g_layers, g_head, dxs, losses, stats,
             aux_sum),
            None,
        )

    act_shape = (pp,) + xs.shape[1:]
    init = (
        _pin_stagewise(mesh, jnp.zeros(act_shape, xs.dtype)),
        _pin_stagewise(mesh, jnp.zeros(act_shape, xs.dtype)),
        _pin_stagewise(
            mesh, jnp.zeros((pp, S) + xs.shape[1:], xs.dtype), token_dim=2
        ),
        jax.tree.map(jnp.zeros_like, layers_s),
        jax.tree.map(jnp.zeros_like, head_params),
        jnp.zeros_like(xs),
        jnp.zeros((M,), jnp.float32),
        jax.tree.map(
            lambda s: jnp.zeros((M,) + s.shape, s.dtype), stats_shape
        ),
        jnp.float32(0.0),
    )
    (_, _, _, g_layers, g_head, dxs, losses, stats, aux_sum), _ = jax.lax.scan(
        round_fn, init, jnp.arange(rounds)
    )
    g_layers = jax.tree.map(
        lambda g: g.reshape((g.shape[0] * g.shape[1],) + g.shape[2:]), g_layers
    )
    return losses, stats, aux_sum, g_layers, g_head, dxs


def pipeline_1f1b_interleaved_grads(
    mesh: Mesh,
    stage_fn: Callable[[Any, jax.Array, Any], tuple[jax.Array, jax.Array]],
    head_loss_fn: Callable[[Any, jax.Array, Any], tuple[jax.Array, Any]],
    layers: Any,
    head_params: Any,
    xs: jax.Array,
    aux_inputs: Any,
    mb_data: Any,
    weights: jax.Array,
    *,
    virtual: int,
    aux_coef: float = 0.0,
) -> tuple[jax.Array, Any, jax.Array, Any, Any, jax.Array]:
    """Interleaved-virtual-stage 1F1B (see module docstring timetable).

    Same contract as `pipeline_1f1b_grads` — explicit per-chunk `jax.vjp`
    backwards, gradients returned, nothing autodiffs through the round scan
    — but each rank cycles through its v non-contiguous chunks, shrinking
    the warmup/cooldown bubble ~1/v. `layers` must be in the chunk-major
    interleaved storage layout (`interleave_layer_indices`); the returned
    g_layers is in that same layout.

    At v=1 the timetable, stash occupancy and accumulation order all reduce
    exactly to `pipeline_1f1b_grads` — the bitwise oracle for this path
    (tests/test_pipeline_interleaved.py).
    """
    pp = mesh.shape[mesh_lib.AXIS_PP]
    v = int(virtual)
    M = xs.shape[0]
    delta = v * pp - 1
    sizes = _interleaved_stash_sizes(pp, v, M)
    offs = [0]
    for sz in sizes[:-1]:
        offs.append(offs[-1] + sz)
    S_total = sum(sizes)
    off_arr = jnp.asarray(offs, jnp.int32)
    size_arr = jnp.asarray(sizes, jnp.int32)
    # last backward: B(M-1, chunk 0) on rank 0
    rounds = (
        delta
        + ((M - 1) // pp) * v * pp
        + (v - 1) * pp
        + (M - 1) % pp
        + pp
    )
    stages = jnp.arange(pp)
    layers_c = _chunk_stack(layers, pp, v)

    _, stats_shape = jax.eval_shape(
        head_loss_fn, head_params, jax.ShapeDtypeStruct(xs.shape[1:], xs.dtype),
        jax.eval_shape(lambda t: _index_mb(t, 0), mb_data),
    )

    def round_fn(carry, r):
        (fwd_in, bwd_in, stash, g_layers, g_head, dxs, losses, stats,
         aux_sum) = carry

        # ---- one forward chunk per rank -------------------------------
        vcf, _, mf_c, f_valid = _fwd_decode(r, stages, pp, v, M)
        fresh = jax.lax.dynamic_index_in_dim(xs, mf_c[0], 0, keepdims=False)
        entry = (stages == 0) & (vcf == 0)
        x_in = jnp.where(entry[:, None, None], fresh[None], fwd_in)
        y, aux_f = jax.vmap(stage_fn)(
            jax.vmap(_pick_chunk)(layers_c, vcf),
            x_in,
            _gather_per_stage(aux_inputs, mf_c),
        )
        aux_sum = aux_sum + jnp.sum(jnp.where(f_valid, aux_f, 0.0))
        slot_f = jnp.take(off_arr, vcf) + mf_c % jnp.take(size_arr, vcf)
        stash = jax.vmap(_masked_row_write)(stash, x_in, slot_f, f_valid)

        # ---- head + loss + seed when the LAST chunk's forward lands ----
        l_valid = f_valid[pp - 1] & (vcf[pp - 1] == v - 1)
        m_last_c = mf_c[pp - 1]
        mb_m = _index_mb(mb_data, m_last_c)
        w_m = jnp.where(
            l_valid,
            jax.lax.dynamic_index_in_dim(weights, m_last_c, 0, keepdims=False),
            0.0,
        )
        loss_m, head_vjp, stats_m = jax.vjp(
            lambda hp, y_: head_loss_fn(hp, y_, mb_m),
            head_params,
            y[pp - 1],
            has_aux=True,
        )
        g_head_m, dy = head_vjp(jnp.zeros_like(loss_m) + w_m)
        g_head = jax.tree.map(jnp.add, g_head, g_head_m)
        losses = _masked_row_write(losses, loss_m, m_last_c, l_valid)
        stats = jax.tree.map(
            lambda b, val: _masked_row_write(b, val, m_last_c, l_valid),
            stats,
            stats_m,
        )

        # ---- one backward chunk per rank ------------------------------
        nb = r - delta - (pp - 1 - stages)
        ub = nb % pp
        vcb = v - 1 - ((nb // pp) % v)
        mb_idx = (nb // (pp * v)) * pp + ub
        b_valid = (nb >= 0) & (mb_idx < M)
        mb_c = jnp.clip(mb_idx, 0, M - 1)
        # B(m, C-1) runs the same round as F(m, C-1): seed from this
        # round's head vjp; every other chunk receives the rolled gx.
        seed = (stages == pp - 1) & (vcb == v - 1)
        g_in = jnp.where(seed[:, None, None], dy[None], bwd_in)
        g_in = jnp.where(b_valid[:, None, None], g_in, 0.0)
        g_aux = jnp.where(b_valid, jnp.float32(aux_coef), 0.0)
        slot_b = jnp.take(off_arr, vcb) + mb_c % jnp.take(size_arr, vcb)
        x_saved = jax.vmap(
            lambda st, slot: jax.lax.dynamic_index_in_dim(
                st, slot, 0, keepdims=False
            )
        )(stash, slot_b)
        aux_b = _gather_per_stage(aux_inputs, mb_c)

        def stage_bwd(layers_local, x, aux_t, gy, ga):
            _, vjp = jax.vjp(
                lambda L_, x_: stage_fn(L_, x_, aux_t), layers_local, x
            )
            return vjp((gy.astype(x.dtype), ga))

        g_layers_m, gx = jax.vmap(stage_bwd)(
            jax.vmap(_pick_chunk)(layers_c, vcb), x_saved, aux_b, g_in, g_aux
        )

        # accumulate into the rank's chunk slot vcb (invalid rounds add
        # exact zeros — g_in/g_aux were zeroed, vjp is linear)
        def acc_rank(gl, gm, vc_i):
            prev = jax.lax.dynamic_index_in_dim(gl, vc_i, 0, keepdims=False)
            return jax.lax.dynamic_update_index_in_dim(gl, prev + gm, vc_i, 0)

        g_layers = jax.tree.map(
            lambda gl, gm: jax.vmap(acc_rank)(gl, gm, vcb), g_layers,
            g_layers_m,
        )
        # rank 0 finishing chunk 0's backward yields d/d(xs[m])
        dxs = _masked_row_write(
            dxs, gx[0], mb_c[0], b_valid[0] & (vcb[0] == 0)
        )

        fwd_in = _pin_stagewise(mesh, jnp.roll(y, 1, axis=0))
        bwd_in = _pin_stagewise(mesh, jnp.roll(gx, -1, axis=0))
        return (
            (fwd_in, bwd_in, stash, g_layers, g_head, dxs, losses, stats,
             aux_sum),
            None,
        )

    act_shape = (pp,) + xs.shape[1:]
    init = (
        _pin_stagewise(mesh, jnp.zeros(act_shape, xs.dtype)),
        _pin_stagewise(mesh, jnp.zeros(act_shape, xs.dtype)),
        _pin_stagewise(
            mesh, jnp.zeros((pp, S_total) + xs.shape[1:], xs.dtype),
            token_dim=2,
        ),
        jax.tree.map(jnp.zeros_like, layers_c),
        jax.tree.map(jnp.zeros_like, head_params),
        jnp.zeros_like(xs),
        jnp.zeros((M,), jnp.float32),
        jax.tree.map(
            lambda s: jnp.zeros((M,) + s.shape, s.dtype), stats_shape
        ),
        jnp.float32(0.0),
    )
    (_, _, _, g_layers, g_head, dxs, losses, stats, aux_sum), _ = jax.lax.scan(
        round_fn, init, jnp.arange(rounds)
    )
    # [pp, v, Lc, ...] → [L, ...] in the chunk-major storage layout
    g_layers = jax.tree.map(
        lambda g: g.reshape((g.shape[0] * g.shape[1] * g.shape[2],)
                            + g.shape[3:]),
        g_layers,
    )
    return losses, stats, aux_sum, g_layers, g_head, dxs
