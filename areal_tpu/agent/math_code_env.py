"""Math+code single-step environment (parity:
realhf/impl/environment/math_code_single_step_env.py:42
MathCodeSingleStepEnv).

The env owns per-question metadata (`id2info`: qid -> {"task": "math"|
"code", ...}) and `step((qid, answers))` dispatches the whole GRPO group
to the matching verifier:

- math: LaTeX-equivalence grading (areal_tpu.reward.math_parser) against
  the question's `solutions`;
- code: the sandboxed subprocess test-case runner
  (areal_tpu.reward.code_verify) against `input_output` testcases.

Both verifiers run in worker threads so the asyncio rollout loop never
blocks on sympy or subprocess wall time.
"""

from __future__ import annotations

import asyncio
from typing import Any

from areal_tpu.api.agent_api import EnvironmentService, register_environment
from areal_tpu.utils import logging

logger = logging.getLogger("math_code_env")


class MathCodeSingleStepEnv(EnvironmentService):
    def __init__(self, id2info: dict[str, dict[str, Any]]):
        self.id2info = dict(id2info)

    async def reset(self, seed=None, options=None):
        if options and "id2info" in options:
            self.id2info = dict(options["id2info"])
        return None

    async def step(self, action: tuple[str, list[str]]):
        """action = (qid, group answers) -> (None, [0/1 per answer],
        True, False, {"task": ...}). Unknown qids raise — a silent zero
        would poison GRPO advantages with fake all-fail groups."""
        qid, answers = action
        qid = str(qid).split("@")[0]
        info = self.id2info[qid]
        task = info.get("task", "math")
        loop = asyncio.get_running_loop()
        if task == "math":
            rewards = await loop.run_in_executor(
                None, self._verify_math, info, list(answers)
            )
        elif task == "code":
            rewards = await loop.run_in_executor(
                None, self._verify_code, info, list(answers)
            )
        else:
            raise ValueError(f"unknown task {task!r} for qid {qid}")
        return None, [float(r) for r in rewards], True, False, {"task": task}

    @staticmethod
    def _verify_math(info: dict, answers: list[str]) -> list[int]:
        # batch seam: offloads to the verify service when
        # AREAL_VERIFIER_SERVICE is set, local thread-pool grading
        # otherwise (parity: math_verify_call switch in the reference env)
        from areal_tpu.reward.remote_verify import batch_math_verify

        qids = ["q"] * len(answers)
        return batch_math_verify({"q": info}, list(answers), qids)

    @staticmethod
    def _verify_code(info: dict, answers: list[str]) -> list[int]:
        from areal_tpu.reward.remote_verify import batch_code_verify

        qids = ["q"] * len(answers)
        return batch_code_verify({"q": info}, list(answers), qids)


register_environment("math-code-single-step", MathCodeSingleStepEnv)
