"""Math single-step agent + env (parity:
realhf/impl/agent/math_single_step_agent.py:23,
realhf/impl/environment/math_code_single_step_env.py).

One step: the agent samples `group_size` answers for the prompt, the env
verifies each against the reference answer (sympy/latex equivalence via
areal_tpu.reward.math_parser), and the episode becomes one GRPO group of
training rows.
"""

from __future__ import annotations

import asyncio
import uuid
from typing import Any

import numpy as np

from areal_tpu.api.agent_api import Agent, EnvironmentService
from areal_tpu.api.cli_args import GenerationHyperparameters
from areal_tpu.api.io_struct import ModelRequest
from areal_tpu.api.workflow_api import RolloutWorkflow
from areal_tpu.utils.data import pad_sequences_to_tensors


class MathSingleStepEnv(EnvironmentService):
    """Stateless verifier env: step(answers) scores them against the
    prompt's reference answer."""

    def __init__(self, answer: str | None = None, reward_fn=None):
        self.answer = answer
        if reward_fn is None:
            from areal_tpu.reward.math_parser import math_verify_reward

            reward_fn = lambda completion, answer: math_verify_reward(  # noqa: E731
                None, completion, answer=answer
            )
        self.reward_fn = reward_fn

    async def reset(self, seed=None, options=None):
        if options and "answer" in options:
            self.answer = options["answer"]
        return None

    async def step(self, action: list[str]):
        loop = asyncio.get_running_loop()
        rewards = await asyncio.gather(
            *[
                loop.run_in_executor(None, self.reward_fn, a, self.answer)
                for a in action
            ]
        )
        return None, [float(r) for r in rewards], True, False, {}


class MathSingleStepAgent(Agent):
    def __init__(
        self,
        gconfig: GenerationHyperparameters,
        tokenizer: Any,
        success_rate_lb: float = 0.0,
        success_rate_ub: float = 1.0,
    ):
        self.gconfig = gconfig
        self.tokenizer = tokenizer
        # Episode filters (parity: the reference agent rejects prompt groups
        # that are all-solved or all-failed beyond these bounds).
        self.success_rate_lb = success_rate_lb
        self.success_rate_ub = success_rate_ub

    def _encode(self, prompt: dict[str, Any]) -> list[int]:
        if "input_ids" in prompt:
            return list(np.asarray(prompt["input_ids"]).reshape(-1))
        if "messages" in prompt:
            return self.tokenizer.apply_chat_template(
                prompt["messages"], add_generation_prompt=True, tokenize=True
            )
        return self.tokenizer.encode(prompt["question"])

    async def collect_trajectory(self, engine, prompt, env):
        await env.reset(options={"answer": prompt.get("answer")})
        ids = self._encode(prompt)
        n = self.gconfig.n_samples
        req = ModelRequest(
            rid=str(uuid.uuid4()),
            input_ids=ids,
            gconfig=self.gconfig.new(n_samples=1),
            tokenizer=self.tokenizer,
        )
        resps = await asyncio.gather(
            *[engine.agenerate(req.copy()) for _ in range(n)]
        )
        answers = [
            self.tokenizer.decode(r.output_tokens) if self.tokenizer else ""
            for r in resps
        ]
        _, rewards, *_ = await env.step(answers)
        rate = float(np.mean([r > 0 for r in rewards]))
        if not (self.success_rate_lb <= rate <= self.success_rate_ub):
            return []  # rejected episode
        rows = []
        for resp, reward in zip(resps, rewards):
            rows.append(
                dict(
                    input_ids=np.array(
                        resp.input_tokens + resp.output_tokens, dtype=np.int32
                    ),
                    loss_mask=np.array(
                        [0] * resp.input_len + [1] * resp.output_len,
                        dtype=np.int32,
                    ),
                    logprobs=np.array(
                        [0.0] * resp.input_len + resp.output_logprobs,
                        dtype=np.float32,
                    ),
                    versions=np.array(
                        [-1] * resp.input_len + resp.output_versions,
                        dtype=np.int32,
                    ),
                    rewards=np.float32(reward),
                    begin_of_answer=np.int32(resp.input_len),
                )
            )
        return rows


class AgentWorkflow(RolloutWorkflow):
    """Adapter: any Agent + env factory becomes a RolloutWorkflow, inheriting
    the async executor's staleness/capacity/interrupt machinery."""

    def __init__(self, agent: Agent, env_factory):
        self.agent = agent
        self.env_factory = env_factory

    async def arun_episode(self, engine, data):
        env = self.env_factory()
        try:
            rows = await self.agent.collect_trajectory(engine, data, env)
        finally:
            await env.close()
        if not rows:
            return None  # rejected
        return pad_sequences_to_tensors(rows)
