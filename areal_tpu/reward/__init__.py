from areal_tpu.reward.math_parser import math_verify_reward  # noqa: F401
