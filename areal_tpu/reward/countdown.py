"""Countdown task reward: reach a target with given numbers.

Parity: /root/reference/examples/countdown/reward_score.py — extract the
`<answer>equation</answer>` from the completion, require every provided
number be used exactly once, evaluate, and score 1.0 on hitting the
target, 0.1 for a well-formed-but-wrong equation (format score), 0
otherwise.

Implementation difference: the equation is evaluated by walking a
restricted AST (+, -, *, / over integer literals) instead of the
reference's regex-guarded `eval` — no code execution surface at all.
"""

from __future__ import annotations

import re

FORMAT_SCORE = 0.1
SCORE = 1.0

_ANSWER_RE = re.compile(r"<answer>(.*?)</answer>", re.DOTALL)


def extract_equation(completion: str) -> str | None:
    matches = _ANSWER_RE.findall(completion)
    return matches[-1].strip() if matches else None


def _safe_eval(expr: str) -> float | None:
    """Integer-only arithmetic evaluation (utils/arith_eval.py): floats
    and digit-grouping literals are scoring exploits here, not numbers."""
    from areal_tpu.utils.arith_eval import safe_eval_arithmetic

    return safe_eval_arithmetic(expr, allow_float=False)


def _uses_numbers_exactly(expr: str, numbers: list[int]) -> bool:
    used = sorted(int(n) for n in re.findall(r"\d+", expr))
    return used == sorted(int(n) for n in numbers)


def countdown_reward(
    prompt, completion, prompt_ids, completion_ids, *, target, numbers, **kw
) -> float:
    """1.0 for a valid equation hitting `target`, 0.1 for a present-but-
    wrong equation, 0.0 otherwise."""
    equation = extract_equation(completion or "")
    if equation is None:
        return 0.0
    if not _uses_numbers_exactly(equation, list(numbers)):
        return FORMAT_SCORE
    value = _safe_eval(equation)
    if value is None:
        return FORMAT_SCORE
    return SCORE if abs(value - float(target)) < 1e-5 else FORMAT_SCORE
