"""Countdown task reward: reach a target with given numbers.

Parity: /root/reference/examples/countdown/reward_score.py — extract the
`<answer>equation</answer>` from the completion, require every provided
number be used exactly once, evaluate, and score 1.0 on hitting the
target, 0.1 for a well-formed-but-wrong equation (format score), 0
otherwise.

Implementation difference: the equation is evaluated by walking a
restricted AST (+, -, *, / over integer literals) instead of the
reference's regex-guarded `eval` — no code execution surface at all.
"""

from __future__ import annotations

import ast
import re

FORMAT_SCORE = 0.1
SCORE = 1.0

_ANSWER_RE = re.compile(r"<answer>(.*?)</answer>", re.DOTALL)


def extract_equation(completion: str) -> str | None:
    matches = _ANSWER_RE.findall(completion)
    return matches[-1].strip() if matches else None


_ALLOWED_CHARS = re.compile(r"[\d+\-*/().\s]+")


def _safe_eval(expr: str) -> float | None:
    """Evaluate an arithmetic expression via a whitelisted AST walk.

    The character whitelist runs FIRST (like the reference's regex guard):
    python literal syntax is richer than countdown arithmetic — e.g. `3_4`
    parses as the int 34 while its digits still pass the uses-each-number
    check, a concatenation exploit an RL policy would find."""
    if not _ALLOWED_CHARS.fullmatch(expr):
        return None
    try:
        tree = ast.parse(expr, mode="eval")
    except SyntaxError:
        return None

    def walk(node) -> float:
        if isinstance(node, ast.Expression):
            return walk(node.body)
        if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.Add, ast.Sub, ast.Mult, ast.Div)
        ):
            a, b = walk(node.left), walk(node.right)
            if isinstance(node.op, ast.Add):
                return a + b
            if isinstance(node.op, ast.Sub):
                return a - b
            if isinstance(node.op, ast.Mult):
                return a * b
            if b == 0:
                raise ZeroDivisionError
            return a / b
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
            return -walk(node.operand)
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            return float(node.value)
        raise ValueError(f"disallowed node {type(node).__name__}")

    try:
        return walk(tree)
    except (ValueError, ZeroDivisionError, RecursionError):
        return None


def _uses_numbers_exactly(expr: str, numbers: list[int]) -> bool:
    used = sorted(int(n) for n in re.findall(r"\d+", expr))
    return used == sorted(int(n) for n in numbers)


def countdown_reward(
    prompt, completion, prompt_ids, completion_ids, *, target, numbers, **kw
) -> float:
    """1.0 for a valid equation hitting `target`, 0.1 for a present-but-
    wrong equation, 0.0 otherwise."""
    equation = extract_equation(completion or "")
    if equation is None:
        return 0.0
    if not _uses_numbers_exactly(equation, list(numbers)):
        return FORMAT_SCORE
    value = _safe_eval(equation)
    if value is None:
        return FORMAT_SCORE
    return SCORE if abs(value - float(target)) < 1e-5 else FORMAT_SCORE
