"""Sandboxed testcase runner for the code-verifier reward.

Executed as a subprocess (`python -m areal_tpu.reward._code_runner`): reads a
JSON spec on stdin, runs the candidate code against each testcase with
per-case alarms and rlimits, writes a JSON verdict on stdout.

Parity: the reference's functioncall/code/function/testing_util.py driven by
local_verify.py (/root/reference/functioncall/code/local_verify.py:37) — the
same two testcase styles:

- **stdio**: the program reads stdin and prints; compare stdout to
  `expectedOutput` (whitespace-normalized, per-line rstrip).
- **function**: call `entryFunction(*args)` with JSON-decoded args; compare
  the return value to the JSON-decoded expected output.

Isolation model matches the reference (a killed-on-timeout subprocess with
resource limits), which is process isolation, not a hard security boundary —
run under an outer sandbox for genuinely hostile code.
"""

from __future__ import annotations

import io
import json
import math
import signal
import sys
import traceback
from contextlib import redirect_stdout


def _apply_rlimits(cpu_seconds: float, memory_mb: int) -> None:
    try:
        import resource

        cpu = max(1, int(math.ceil(cpu_seconds)) + 1)
        resource.setrlimit(resource.RLIMIT_CPU, (cpu, cpu + 1))
        if memory_mb > 0:
            b = memory_mb * 1024 * 1024
            resource.setrlimit(resource.RLIMIT_AS, (b, b))
        # no subprocess bombs from candidate code
        resource.setrlimit(resource.RLIMIT_NPROC, (16, 16))
    except Exception:  # pragma: no cover - platform-dependent
        pass


class _CaseTimeout(Exception):
    pass


def _alarm(_sig, _frm):
    raise _CaseTimeout()


def _norm_stdout(text: str) -> list[str]:
    lines = [ln.rstrip() for ln in text.strip().splitlines()]
    while lines and not lines[-1]:
        lines.pop()
    return lines


def _run_stdio_case(code: str, inp: str) -> str:
    stdin = sys.stdin
    sys.stdin = io.StringIO(inp if inp.endswith("\n") else inp + "\n")
    out = io.StringIO()
    try:
        with redirect_stdout(out):
            g = {"__name__": "__main__", "__builtins__": __builtins__}
            exec(code, g)  # noqa: S102 — sandboxed candidate execution
    finally:
        sys.stdin = stdin
    return out.getvalue()


def _decode_arg(raw):
    try:
        return json.loads(raw)
    except (json.JSONDecodeError, TypeError):
        return raw


def _run_assert_case(code: str, harness: str) -> bool:
    """HumanEval/MBPP-style unit-test harness: exec the candidate, then the
    harness (assert statements / a check(candidate) driver) in the same
    namespace; pass iff nothing raises."""
    g = {"__name__": "__main__", "__builtins__": __builtins__}
    with redirect_stdout(io.StringIO()):
        exec(code, g)  # noqa: S102 — sandboxed candidate execution
        exec(harness, g)  # noqa: S102 — sandboxed test harness
    return True


def _run_function_case(code: str, fn_name: str, inp, expected):
    g = {"__name__": "__main__", "__builtins__": __builtins__}
    with redirect_stdout(io.StringIO()):
        exec(code, g)  # noqa: S102 — sandboxed candidate execution
        fn = g.get(fn_name)
        if fn is None and "Solution" in g:  # LeetCode-style class wrapper
            fn = getattr(g["Solution"](), fn_name, None)
        if fn is None:
            raise NameError(f"entry function {fn_name!r} not defined")
        args = inp if isinstance(inp, list) else [inp]
        got = fn(*args)
    exp = _decode_arg(expected) if isinstance(expected, str) else expected
    if isinstance(got, tuple):
        got = list(got)
    return got == exp


def main() -> None:
    spec = json.load(sys.stdin)
    code = spec["code"]
    fn_name = spec.get("entryFunction") or ""
    timeout = float(spec.get("timeout", 6.0))
    fast_fail = bool(spec.get("isFastFail", True))
    _apply_rlimits(
        cpu_seconds=timeout * max(1, len(spec.get("testcases", []))),
        memory_mb=int(spec.get("memory", 0)),
    )
    signal.signal(signal.SIGALRM, _alarm)

    results = []
    error = None
    for case in spec.get("testcases", []):
        ok = False
        try:
            signal.setitimer(signal.ITIMER_REAL, timeout)
            if case.get("assertCode"):
                ok = _run_assert_case(code, str(case["assertCode"]))
            elif fn_name:
                ok = _run_function_case(
                    code, fn_name, _decode_arg(case["input"]),
                    case["expectedOutput"],
                )
            else:
                out = _run_stdio_case(code, str(case["input"]))
                ok = _norm_stdout(out) == _norm_stdout(
                    str(case["expectedOutput"])
                )
        except _CaseTimeout:
            error = "timeout"
        except BaseException:  # noqa: BLE001 — candidate code can raise anything
            error = traceback.format_exc(limit=3)
        finally:
            signal.setitimer(signal.ITIMER_REAL, 0)
        results.append(bool(ok))
        if fast_fail and not ok:
            break
    json.dump({"results": results, "error": error}, sys.stdout)
    sys.stdout.flush()


if __name__ == "__main__":
    main()
