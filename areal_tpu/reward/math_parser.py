"""Rule-based math answer verification.

Parity target: areal/reward/math_parser.py — extract the final answer from a
model completion (\\boxed{...}, "the answer is ...", last number) and test
mathematical equivalence against the ground truth via sympy when available,
falling back to string/numeric comparison.
"""

from __future__ import annotations

import re

from areal_tpu.utils import logging

logger = logging.getLogger("math_parser")


_BOXED_RE = re.compile(r"\\boxed\s*\{")
_ANSWER_PATTERNS = [
    re.compile(r"(?:final answer|answer)\s*(?:is|:)\s*(.+)", re.IGNORECASE),
]
_NUMBER_RE = re.compile(r"-?\d+(?:[.,]\d+)*(?:/\d+)?")


def extract_boxed(text: str) -> str | None:
    """Extract the LAST \\boxed{...} with balanced braces."""
    last = None
    for m in _BOXED_RE.finditer(text):
        start = m.end()
        depth = 1
        i = start
        while i < len(text) and depth > 0:
            if text[i] == "{":
                depth += 1
            elif text[i] == "}":
                depth -= 1
            i += 1
        if depth == 0:
            last = text[start : i - 1]
    return last


def extract_answer(text: str) -> str | None:
    """Best-effort final-answer extraction from a completion."""
    boxed = extract_boxed(text)
    if boxed is not None:
        return boxed.strip()
    for pat in _ANSWER_PATTERNS:
        matches = pat.findall(text)
        if matches:
            ans = matches[-1].strip().rstrip(".")
            inner = extract_boxed(ans)
            return (inner or ans).strip()
    numbers = _NUMBER_RE.findall(text)
    if numbers:
        return numbers[-1]
    return None


def _normalize(ans: str) -> str:
    ans = ans.strip().strip("$").strip()
    ans = ans.replace("\\!", "").replace("\\,", "").replace("\\ ", " ")
    ans = ans.replace("dfrac", "frac").replace("tfrac", "frac")
    ans = ans.replace("\\left", "").replace("\\right", "")
    ans = ans.replace("^{\\circ}", "").replace("^\\circ", "")
    ans = ans.replace("\\%", "").rstrip("%")
    ans = re.sub(r"\\text\{[^}]*\}", "", ans)
    ans = re.sub(r"\s+", " ", ans).strip()
    # strip thousands separators in plain numbers like 1,234,567
    if re.fullmatch(r"-?\d{1,3}(,\d{3})+(\.\d+)?", ans):
        ans = ans.replace(",", "")
    return ans


def _to_number(ans: str) -> float | None:
    ans = ans.strip()
    m = re.fullmatch(r"(-?\d+)\s*/\s*(\d+)", ans)
    if m:
        denom = float(m.group(2))
        return float(m.group(1)) / denom if denom else None
    frac = re.fullmatch(r"-?\\frac\{(-?\d+)\}\{(-?\d+)\}", ans)
    if frac:
        denom = float(frac.group(2))
        val = float(frac.group(1)) / denom if denom else None
        if val is not None and ans.startswith("-"):
            val = -val
        return val
    try:
        return float(ans)
    except ValueError:
        return None


def math_equal(pred: str, target: str) -> bool:
    """Mathematical equivalence: numeric, then sympy-symbolic, then string."""
    pred, target = _normalize(pred), _normalize(target)
    if pred == target:
        return True
    pn, tn = _to_number(pred), _to_number(target)
    if pn is not None and tn is not None:
        return abs(pn - tn) < 1e-6 * max(1.0, abs(tn))
    try:
        import sympy
        from sympy.parsing.latex import parse_latex

        def parse(s):
            try:
                return parse_latex(s)
            except Exception:
                return sympy.sympify(s)

        diff = sympy.simplify(parse(pred) - parse(target))
        return diff == 0
    except Exception:
        return False


def math_verify_reward(
    prompt: str | None,
    completion: str | None,
    prompt_ids=None,
    completion_ids=None,
    **data,
) -> float:
    """Binary verifiable reward for math answers (the RLVR reward_fn
    signature). Ground truth comes from data['answer'] (or 'solution')."""
    target = data.get("answer", data.get("solution"))
    if completion is None or target is None:
        return 0.0
    target_ans = extract_answer(str(target)) or str(target).strip()
    pred = extract_answer(completion)
    if pred is None:
        return 0.0
    return 1.0 if math_equal(pred, target_ans) else 0.0
