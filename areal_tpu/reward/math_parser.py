"""Rule-based math answer extraction + equivalence grading.

Parity target: areal/reward/math_parser.py (867 lines + the vendored
latex2sympy under /root/reference/evaluation/) — the reference grades
MATH/AIME-style answers by (1) extracting the final answer from a model
completion (\\boxed{...}, "the answer is ...", minerva's "final answer is
$...$. I hope", choice letters, last number), (2) normalizing LaTeX
(units, \\text, degrees, percents, frac/sqrt repair, word numbers,
matrix/interval syntax), and (3) testing equivalence numerically,
structurally (intervals, tuples, matrices, equations) and symbolically.

This environment has no antlr4/latex2sympy, so sympy's parse_latex is
unusable; `_latex_to_expr` is a self-contained LaTeX -> SymPy translator
covering the answer grammar that actually occurs in math benchmarks
(fractions, roots, powers, constants, trig/log, implicit multiplication).
Everything here is pure host-side Python — nothing touches JAX.
"""

from __future__ import annotations

import re

from areal_tpu.utils import logging

logger = logging.getLogger("math_parser")

# answers longer than this get no sympy attempt (hang/blow-up guard)
_MAX_SYMPY_LEN = 384

# ---------------------------------------------------------------------------
# word numbers
# ---------------------------------------------------------------------------

_UNITS_WORDS = {
    "zero": 0, "one": 1, "two": 2, "three": 3, "four": 4, "five": 5,
    "six": 6, "seven": 7, "eight": 8, "nine": 9, "ten": 10, "eleven": 11,
    "twelve": 12, "thirteen": 13, "fourteen": 14, "fifteen": 15,
    "sixteen": 16, "seventeen": 17, "eighteen": 18, "nineteen": 19,
}
_TENS_WORDS = {
    "twenty": 20, "thirty": 30, "forty": 40, "fifty": 50,
    "sixty": 60, "seventy": 70, "eighty": 80, "ninety": 90,
}
_SCALE_WORDS = {"hundred": 100, "thousand": 1_000, "million": 1_000_000,
                "billion": 1_000_000_000}


def word_to_number(text: str) -> int | None:
    """"twenty-five" -> 25, "one hundred seven" -> 107; None if not a
    pure spelled-out number."""
    words = re.split(r"[\s-]+", text.strip().lower())
    if not words or any(
        w not in _UNITS_WORDS and w not in _TENS_WORDS
        and w not in _SCALE_WORDS and w != "and"
        for w in words
    ):
        return None
    total = group = 0
    seen = False
    for w in words:
        if w == "and":
            continue
        seen = True
        if w in _UNITS_WORDS:
            group += _UNITS_WORDS[w]
        elif w in _TENS_WORDS:
            group += _TENS_WORDS[w]
        else:
            scale = _SCALE_WORDS[w]
            if scale == 100:
                group = max(group, 1) * 100
            else:
                total += max(group, 1) * scale
                group = 0
    return total + group if seen else None


# ---------------------------------------------------------------------------
# units (MathQA-style suffixes that must not break numeric grading)
# ---------------------------------------------------------------------------

_UNIT_TEXTS = [
    "degrees", "degree", "deg", "radians", "radian",
    "dollars", "dollar", "cents", "cent", "rupees", "rupee", "rs",
    "percent", "points", "point",
    "meters", "meter", "metres", "metre", "km", "cm", "mm", "mi",
    "miles", "mile", "feet", "foot", "ft", "inches", "inch", "yards",
    "yard", "units", "unit",
    "mph", "kmph", "kmh", "m/s",
    "sq", "square", "cubic", "cu",
    "liters", "liter", "litres", "litre", "ml", "gallons", "gallon",
    "kg", "grams", "gram", "gm", "g", "lbs", "lb", "ounces", "ounce", "oz",
    "hours", "hour", "hrs", "hr", "minutes", "minute", "min", "seconds",
    "second", "sec", "days", "day", "weeks", "week", "months", "month",
    "years", "year", "yr",
    "apples", "apple", "people", "men", "man", "women", "woman",
    "students", "student", "ways", "way",
]
# longest first so "meters" wins over "m"
_UNIT_TEXTS.sort(key=len, reverse=True)


def _strip_units(s: str) -> str:
    # "times" is special: as a trailing unit ("8 times") it must strip,
    # but mid-string it is multiplication phrasing ("4 times 5") whose
    # removal would CONCATENATE the operands into a wrong number after
    # the later space removal. (\times stays: protected by the backslash
    # guard below.)
    s = re.sub(r"(?<=\d)\s*times\s*$", "", s)
    # (?<![\\A-Za-z]) guards LaTeX commands: "min"/"sec"/"deg" must not
    # eat \min, \sec^2, \deg — a backslash or letter before the word means
    # it is (part of) a command, not a unit suffix.
    for u in _UNIT_TEXTS:
        s = re.sub(rf"(?<![\\A-Za-z]){re.escape(u)}(?![A-Za-z])", "", s)
    return s


# ---------------------------------------------------------------------------
# LaTeX repair / canonicalization
# ---------------------------------------------------------------------------


def _fix_fracs(s: str) -> str:
    """\\frac12 -> \\frac{1}{2}; \\frac1{72} -> \\frac{1}{72};
    \\fracab -> \\frac{a}{b}. Already-braced args pass through."""

    def brace_two(rest: str) -> str:
        out = []
        for _ in range(2):
            rest = rest.lstrip()
            if not rest:
                return None  # type: ignore[return-value]
            if rest[0] == "{":
                depth, i = 1, 1
                while i < len(rest) and depth:
                    depth += rest[i] == "{"
                    depth -= rest[i] == "}"
                    i += 1
                if depth:
                    return None  # type: ignore[return-value]
                out.append(rest[:i])
                rest = rest[i:]
            else:
                out.append("{" + rest[0] + "}")
                rest = rest[1:]
        return "".join(out) + rest

    parts = s.split("\\frac")
    fixed = parts[0]
    for rest in parts[1:]:
        braced = brace_two(rest)
        if braced is None:
            fixed += "\\frac" + rest
        else:
            fixed += "\\frac" + braced
    return fixed


def _fix_sqrt(s: str) -> str:
    """\\sqrt5 -> \\sqrt{5}; \\sqrt ab -> \\sqrt{a}b."""
    return re.sub(r"\\sqrt\s*([^\s{[])", r"\\sqrt{\1}", s)


def _fix_a_slash_b(s: str) -> str:
    """A bare integer ratio answer a/b -> \\frac{a}{b}."""
    m = re.fullmatch(r"(-?\d+)/(\d+)", s.strip())
    return rf"\frac{{{m.group(1)}}}{{{m.group(2)}}}" if m else s


def normalize_answer(ans: str, strip_units: bool = True) -> str:
    """Canonicalize an extracted answer string (parity:
    areal/reward/math_parser.py strip_string, :219-357)."""
    s = str(ans).strip().replace("\n", "")
    s = s.rstrip(".").rstrip("/").lstrip(":").strip()
    s = s.replace("\\!", "").replace("\\,", "").replace("\\;", "")
    s = s.replace("\\:", "").replace("~", " ")

    # matrix environments: array/bmatrix/vmatrix all compare as pmatrix
    s = re.sub(r"\\begin\{array\}\{[^}]*\}", r"\\begin{pmatrix}", s)
    s = s.replace(r"\end{array}", r"\end{pmatrix}")
    s = s.replace("bmatrix", "pmatrix").replace("vmatrix", "pmatrix")

    s = s.replace("tfrac", "frac").replace("dfrac", "frac").replace("cfrac", "frac")
    s = s.replace("\\neq", "\\ne").replace("\\leq", "\\le").replace("\\geq", "\\ge")
    s = s.replace("\\left", "").replace("\\right", "")
    s = s.replace("\\{", "{").replace("\\}", "}")

    # trailing \text{...} is a unit ("5 \text{ miles}" -> "5")
    trimmed = re.sub(r"\\text\s*\{.*?\}\s*$", "", s).strip()
    if trimmed:
        s = trimmed
    # interior \text{x} -> x
    s = re.sub(r"\\text\s*\{(.*?)\}", r"\1", s)
    s = re.sub(r"\\mbox\s*\{.*?\}", "", s)
    s = s.replace("\\mathbf", "").replace("\\bf", "").replace("\\mathrm", "")
    s = re.sub(r"\\operatorname\s*\{(.*?)\}", r"\1", s)

    # degrees / dollars / percent decorations
    s = s.replace("^{\\circ}", "").replace("^\\circ", "")
    s = s.replace("\\$", "").replace("$", "")
    s = s.replace("\\(", "").replace("\\)", "")
    s = s.replace("\\%", "").replace("%", "")

    if strip_units:
        s = _strip_units(s)

    w = word_to_number(s)
    if w is not None:
        return str(w)

    # variable-binding prefixes: "x = 5", "x \in [2, 3)"
    for key in ("x=", "y=", "z=", "x\\in", "y\\in", "z\\in",
                "x\\to", "y\\to", "z\\to"):
        s = s.replace(key, "")
    s = s.replace("\\emptyset", "{}")
    s = s.replace("(-\\infty,\\infty)", "\\mathbb{R}")

    s = s.replace("infinity", "\\infty")
    if "\\infty" not in s:
        s = s.replace("inf", "\\infty")

    # bare leading decimal points
    s = s.replace(" .", " 0.").replace("{.", "{0.")
    if s.startswith("."):
        s = "0" + s

    # trailing zero decimals: 5.000 -> 5 (also inside expressions)
    s = re.sub(r"(\d+)\.0+($|[^\d])", r"\1\2", s)

    # "k = <rhs>" with a short LHS -> rhs
    parts = s.split("=")
    if len(parts) == 2 and len(parts[0].strip()) <= 2:
        s = parts[1]

    s = _fix_sqrt(s)
    s = s.replace(" ", "")
    s = _fix_fracs(s)
    s = _fix_a_slash_b(s)

    # plain thousands separators: 1,234,567(.89)
    if re.fullmatch(r"-?\d{1,3}(,\d{3})+(\.\d+)?", s):
        s = s.replace(",", "")
    return s


# ---------------------------------------------------------------------------
# extraction
# ---------------------------------------------------------------------------

_BOXED_RE = re.compile(r"\\boxed\s*\{|\\fbox\s*\{")
_CHOICE_RE = re.compile(r"\b([A-E])\b")
_LAST_NUMBER_RE = re.compile(r"-?\d*\.?\d+")

_CHOICE_DATASETS = ("mmlu", "sat_math", "aqua", "gaokao2023")
_KEEP_UNIT_DATASETS = ("carp_en", "minerva_math")


def extract_boxed(text: str) -> str | None:
    """The LAST \\boxed{...}/\\fbox{...} with balanced braces."""
    last = None
    for m in _BOXED_RE.finditer(text):
        start = m.end()
        depth, i = 1, start
        while i < len(text) and depth > 0:
            depth += text[i] == "{"
            depth -= text[i] == "}"
            i += 1
        if depth == 0:
            last = text[start : i - 1]
    return last


def choice_answer_clean(pred: str) -> str:
    """Reduce a prediction to its last standalone choice letter A-E."""
    pred = pred.strip("\n").rstrip(".").rstrip("/").strip().lstrip(":")
    found = _CHOICE_RE.findall(pred.upper())
    out = found[-1] if found else pred.strip().strip(".")
    return out.rstrip(".").rstrip("/")


def extract_answer(
    text: str,
    data_name: str = "math",
    use_last_number: bool = True,
) -> str | None:
    """Final-answer extraction (parity: reference extract_answer :360-427).

    Order: multiple-choice datasets -> minerva "final answer is $...$.
    I hope" -> \\boxed -> "the answer is" -> last number."""
    if text is None:
        return None
    text = str(text)
    if any(k in data_name for k in _CHOICE_DATASETS):
        return choice_answer_clean(text)

    pred: str | None = None
    if "final answer is $" in text and "$. I hope" in text:
        pred = text.split("final answer is $", 1)[1].split("$. I hope", 1)[0]
    elif "boxed" in text or "fbox" in text:
        pred = extract_boxed(text)
        if pred is None:
            # "\boxed 5" (no brace): take up to the next dollar sign
            tail = re.split(r"\\boxed|\\fbox", text)[-1].strip()
            pred = tail.split("$")[0].strip() or None
    elif "he answer is" in text:  # matches The/the
        pred = text.split("he answer is")[-1].strip()
    elif "final answer is" in text:
        pred = text.split("final answer is")[-1].strip()
    if pred is None and use_last_number:
        nums = _LAST_NUMBER_RE.findall(text.replace(",", ""))
        pred = nums[-1] if nums else None
    if pred is None:
        return None
    pred = re.sub(r"\n\s*", "", pred).strip()
    return normalize_answer(
        pred, strip_units=not any(k in data_name for k in _KEEP_UNIT_DATASETS)
    )


# ---------------------------------------------------------------------------
# numbers
# ---------------------------------------------------------------------------


def parse_number(s: str) -> float | None:
    """Float value of a numeric-looking answer: plain floats, thousands
    separators, percents, \\frac{a}{b}, a/b, mixed numbers 1\\frac{1}{2}."""
    s = str(s).strip().replace(",", "")
    if not s:
        return None
    try:
        return float(s)
    except ValueError:
        pass
    if s.endswith("\\%"):
        s = s[:-2]
    if s.endswith("%"):
        s = s[:-1]
        try:
            return float(s) / 100.0
        except ValueError:
            return None
    m = re.fullmatch(r"(-?)(\d+)?\\?frac\{(-?\d+)\}\{(-?\d+)\}", s)
    if m:
        sign = -1.0 if m.group(1) == "-" else 1.0
        whole = float(m.group(2)) if m.group(2) else 0.0
        num, den = float(m.group(3)), float(m.group(4))
        if den == 0:
            return None
        frac = num / den
        return sign * (whole + frac) if whole else sign * frac
    m = re.fullmatch(r"(-?\d+(?:\.\d+)?)\s*/\s*(-?\d+(?:\.\d+)?)", s)
    if m:
        den = float(m.group(2))
        return float(m.group(1)) / den if den else None
    return None


def numeric_equal(a: float, b: float, rel_tol: float = 1e-4) -> bool:
    from math import isclose

    return isclose(a, b, rel_tol=rel_tol, abs_tol=1e-10)


# ---------------------------------------------------------------------------
# LaTeX -> sympy (antlr-free)
# ---------------------------------------------------------------------------

_FUNC_NAMES = ("arcsin", "arccos", "arctan", "sinh", "cosh", "tanh",
               "sin", "cos", "tan", "sec", "csc", "cot", "log", "ln", "exp")


def _latex_to_pystr(s: str) -> str:
    """Translate the benchmark-answer LaTeX subset to a sympify-able
    string. Raises ValueError on syntax this grammar does not cover."""
    s = s.strip()
    if len(s) > _MAX_SYMPY_LEN:
        raise ValueError("expression too long")
    # \frac{a}{b} (recursive, innermost first)
    pat_frac = re.compile(r"\\frac\{([^{}]*)\}\{([^{}]*)\}")
    pat_root = re.compile(r"\\sqrt\[([^\[\]{}]*)\]\{([^{}]*)\}")
    pat_sqrt = re.compile(r"\\sqrt\{([^{}]*)\}")
    for _ in range(24):
        new = pat_frac.sub(r"((\1)/(\2))", s)
        new = pat_root.sub(r"((\2)**(1/(\1)))", new)
        new = pat_sqrt.sub(r"(sqrt(\1))", new)
        if new == s:
            break
        s = new
    if "\\frac" in s or "\\sqrt" in s:
        raise ValueError("unresolved frac/sqrt")
    s = s.replace("\\cdot", "*").replace("\\times", "*").replace("\\div", "/")
    s = s.replace("\\pi", "pi").replace("\\infty", "oo").replace("\\ne", "!=")
    s = s.replace("\\pm", "+")  # caller splits \pm variants beforehand
    for f in _FUNC_NAMES:
        s = s.replace("\\" + f, f)
    s = s.replace("\\theta", "theta").replace("\\alpha", "alpha")
    s = s.replace("\\beta", "beta").replace("\\gamma", "gamma")
    s = s.replace("\\lambda", "lam").replace("\\mu", "mu")
    s = s.replace("^", "**")
    # {..} grouping -> (..), subscripts x_{1} -> x_1
    s = re.sub(r"_\{([A-Za-z0-9]+)\}", r"_\1", s)
    s = s.replace("{", "(").replace("}", ")")
    s = s.replace("ln(", "log(")
    if "\\" in s:
        raise ValueError(f"unhandled latex command in {s!r}")
    return s


def _to_sympy(s: str):
    import sympy
    from sympy.parsing.sympy_parser import (
        implicit_multiplication_application,
        parse_expr,
        standard_transformations,
    )

    py = _latex_to_pystr(s)
    return parse_expr(
        py,
        transformations=standard_transformations
        + (implicit_multiplication_application,),
        evaluate=True,
        local_dict={"oo": sympy.oo, "pi": sympy.pi},
    )


def symbolic_equal(a: str, b: str) -> bool:
    """sympy equivalence: simplify(a - b) == 0, with numeric fallback."""
    import sympy

    try:
        ea, eb = _to_sympy(a), _to_sympy(b)
    except Exception:
        return False
    try:
        if ea == eb:
            return True
    except Exception:
        pass
    try:
        if sympy.simplify(ea - eb) == 0:
            return True
    except Exception:
        pass
    try:
        na, nb = complex(sympy.N(ea, 15)), complex(sympy.N(eb, 15))
        return abs(na - nb) <= 1e-6 * max(1.0, abs(nb))
    except Exception:
        return False


# ---------------------------------------------------------------------------
# structured comparisons
# ---------------------------------------------------------------------------


def _split_top_level(s: str, sep: str = ",") -> list[str]:
    """Split on sep at brace/bracket/paren depth zero (commas inside
    \\frac{}{} or nested tuples do not split)."""
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch in "{[(":
            depth += 1
        elif ch in "}])":
            depth -= 1
        if ch == sep and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return parts


_MAT_OPEN = "\\begin{pmatrix}"
_MAT_CLOSE = "\\end{pmatrix}"


def _matrix_cells(s: str) -> list[list[str]] | None:
    s = s.strip()
    if not (s.startswith(_MAT_OPEN) and s.endswith(_MAT_CLOSE)):
        return None
    body = s[len(_MAT_OPEN) : -len(_MAT_CLOSE)]
    rows = [r.strip() for r in body.split("\\\\") if r.strip()]
    return [[c.strip() for c in row.split("&")] for row in rows]


def set_to_pmatrix(s: str) -> str:
    """{a, b} column-set notation -> pmatrix (the reference's
    str_to_pmatrix bridge for set-style matrix ground truths)."""
    mats = []
    for m in re.findall(r"\{[^{}]*,[^{}]*\}", s):
        body = m.strip("{}").replace(",", "\\\\")
        mats.append(_MAT_OPEN + body + _MAT_CLOSE)
    return ", ".join(mats) if mats else s


# ---------------------------------------------------------------------------
# top-level equivalence
# ---------------------------------------------------------------------------


def math_equal(
    pred: str,
    target: str,
    include_percentage: bool = True,
    rel_tol: float = 1e-4,
    _depth: int = 0,
) -> bool:
    """Mathematical equivalence of two (extracted) answers (parity:
    reference math_equal :495-678): string, choice, numeric (with the
    x/100, x, 100x percentage ambiguity), interval/tuple elementwise,
    matrix elementwise, single-equation, then symbolic."""
    if pred is None or target is None or _depth > 4:
        return False
    pred, target = str(pred).strip(), str(target).strip()
    if pred.lower() == target.lower():
        return True
    if target in ("A", "B", "C", "D", "E") and choice_answer_clean(pred) == target:
        return True

    # numeric, including the percent ambiguity (0.5 vs 50 vs 50%)
    pn, tn = parse_number(pred), parse_number(target)
    if pn is not None and tn is not None:
        candidates = [tn / 100, tn, tn * 100] if include_percentage else [tn]
        return any(numeric_equal(pn, c, rel_tol) for c in candidates)

    if not pred:
        return False

    # Equations compare BEFORE normalization (normalize_answer drops short
    # LHSes like "y =", destroying the equation structure): a=b equals c=d
    # iff (a-b) is ±(c-d) symbolically.
    if (
        pred.count("=") == 1
        and target.count("=") == 1
        and _equation_equal(pred, target)
    ):
        return True

    npred, ntarget = normalize_answer(pred), normalize_answer(target)
    if npred.lower() == ntarget.lower():
        return True
    pn, tn = parse_number(npred), parse_number(ntarget)
    if pn is not None and tn is not None:
        candidates = [tn / 100, tn, tn * 100] if include_percentage else [tn]
        return any(numeric_equal(pn, c, rel_tol) for c in candidates)

    # matrix vs set-style ground truth
    if "pmatrix" in npred and "pmatrix" not in ntarget:
        ntarget = set_to_pmatrix(ntarget)
    pm, tm = _matrix_cells(npred), _matrix_cells(ntarget)
    if pm is not None and tm is not None:
        if len(pm) != len(tm):
            return False
        for prow, trow in zip(pm, tm):
            if len(prow) != len(trow):
                return False
            for pc, tc in zip(prow, trow):
                if not math_equal(pc, tc, include_percentage, rel_tol,
                                  _depth + 1):
                    return False
        return True

    # bare-vs-bracketed sets: {3} == 3, (1,2) == [1,2] contents
    bare_p = npred.strip("{}()[]")
    bare_t = ntarget.strip("{}()[]")
    if bare_p.lower() == bare_t.lower() and "," not in bare_p:
        return True

    # intervals / tuples: [a, b) vs [c, d) -> elementwise. Bracket
    # openness is deliberately NOT compared — reference parity (its
    # interval branch, math_parser.py:573-590, strips the brackets and
    # compares contents only).
    def enclosed(s: str) -> bool:
        return len(s) >= 2 and s[0] in "([{" and s[-1] in ")]}"

    if enclosed(npred) and enclosed(ntarget):
        pp = _split_top_level(npred[1:-1])
        tp = _split_top_level(ntarget[1:-1])
        if len(pp) == len(tp) and len(pp) > 1:
            if all(
                math_equal(a, b, include_percentage, rel_tol, _depth + 1)
                for a, b in zip(pp, tp)
            ):
                return True

    # equations surviving normalization (long LHSes): same ± diff rule
    if npred.count("=") == 1 and ntarget.count("=") == 1:
        if _equation_equal(npred, ntarget):
            return True
    elif npred.count("=") == 1 and "=" not in ntarget:
        lhs, rhs = npred.split("=")
        if len(lhs.strip()) <= 2 and math_equal(
            rhs, ntarget, include_percentage, rel_tol, _depth + 1
        ):
            return True
    elif ntarget.count("=") == 1 and "=" not in npred:
        lhs, rhs = ntarget.split("=")
        if len(lhs.strip()) <= 2 and math_equal(
            npred, rhs, include_percentage, rel_tol, _depth + 1
        ):
            return True

    # \pm expansion: "1 \pm \sqrt{2}" equals the pair {1+\sqrt2, 1-\sqrt2}
    if "\\pm" in npred or "\\pm" in ntarget:
        def expand(s):
            if "\\pm" in s:
                return [s.replace("\\pm", "+", 1), s.replace("\\pm", "-", 1)]
            return [s]
        pv, tv = expand(npred), expand(ntarget)
        if len(pv) == len(tv) and len(pv) == 2:
            if all(
                math_equal(a, b, include_percentage, rel_tol, _depth + 1)
                for a, b in zip(pv, tv)
            ):
                return True

    return symbolic_equal(npred, ntarget)


def _equation_equal(pred: str, target: str) -> bool:
    """a=b equals c=d iff (a-b) is ±(c-d) symbolically. Sides are
    normalized independently so '=' survives."""
    pl, pr = (normalize_answer(x) for x in pred.split("="))
    tl, tr = (normalize_answer(x) for x in target.split("="))
    pdiff = f"({pl})-({pr})"
    tdiff = f"({tl})-({tr})"
    return symbolic_equal(pdiff, tdiff) or symbolic_equal(f"-({pdiff})", tdiff)


def _math_equal_worker(q, pred: str, target: str) -> None:
    """Module-level so the forkserver context can pickle it."""
    try:
        q.put(bool(math_equal(pred, target)))
    except Exception:  # noqa: BLE001 — any grading crash is a False
        q.put(False)


_GRADING_CTX = None


def _grading_ctx():
    """Forkserver multiprocessing context for grading workers.

    The graders run inside thread pools (remote_verify / verify_server);
    fork-from-threads is deprecated in 3.12 and can inherit a wedged lock
    state that silently grades 0. A forkserver's children fork from a
    clean single-threaded server process instead. sympy is preloaded into
    the server so each grading child gets it by fork, not by import.
    """
    global _GRADING_CTX
    if _GRADING_CTX is None:
        import multiprocessing as mp

        ctx = mp.get_context("forkserver")
        try:
            # "__main__" keeps the default behaviour of importing the
            # caller's main module ONCE in the server (children then fork
            # with it loaded); dropping it would make every grading child
            # re-import a possibly heavy entrypoint inside its timeout.
            ctx.set_forkserver_preload(
                ["__main__", "sympy", "areal_tpu.reward.math_parser"]
            )
        except Exception:  # noqa: BLE001 — preload is an optimization only
            pass
        _GRADING_CTX = ctx
    return _GRADING_CTX


def math_equal_subprocess(pred: str, target: str, timeout_s: float = 5.0) -> bool:
    """math_equal in a worker process with a hard timeout — sympy can hang
    on adversarial inputs; batch eval graders use this (parity: reference
    call_with_timeout + pebble ProcessPool, math_parser.py:684-744).

    A child that wedges anyway is terminated at timeout_s and graded False.
    """
    ctx = _grading_ctx()
    q = ctx.Queue()
    p = ctx.Process(target=_math_equal_worker, args=(q, pred, target), daemon=True)
    p.start()
    p.join(timeout_s)
    if p.is_alive():
        p.terminate()
        p.join()
        return False
    try:
        return q.get(timeout=1.0)
    except Exception:  # noqa: BLE001 — lost result is a False grade
        if p.exitcode != 0:
            # Forkserver children import the caller's __main__; a script
            # without an `if __name__ == "__main__"` guard dies here and
            # every grade silently becomes False. Make that loud.
            import logging

            logging.getLogger("math_parser").warning(
                "grading worker died rc=%s before producing a result; "
                "if this is a script, it needs a __main__ guard "
                "(forkserver re-imports the main module)",
                p.exitcode,
            )
        return False


# ---------------------------------------------------------------------------
# reward fn
# ---------------------------------------------------------------------------


def math_verify_reward(
    prompt: str | None,
    completion: str | None,
    prompt_ids=None,
    completion_ids=None,
    **data,
) -> float:
    """Binary verifiable reward for math answers (the RLVR reward_fn
    signature). Ground truth comes from data['answer'] (or 'solution')."""
    target = data.get("answer", data.get("solution"))
    if completion is None or target is None:
        return 0.0
    target_ans = _extract_ground_truth(str(target))
    pred = extract_answer(completion)
    if pred is None:
        return 0.0
    return 1.0 if math_equal(pred, target_ans) else 0.0


def _extract_ground_truth(target: str) -> str:
    """Ground truths are usually the bare answer already; only unwrap a
    \\boxed/answer-phrase if present. The last-number fallback is for model
    COMPLETIONS — on a raw LaTeX gt like "\\frac{1}{2}" it would mangle the
    answer to "2" and invert the reward. Prose solutions (multi-word text
    with no box) still get the last-number treatment."""
    ans = extract_answer(target, use_last_number=False)
    if ans is not None:
        return ans
    looks_like_prose = len(target) > 64 or re.search(
        r"[A-Za-z]{3,}\s+[A-Za-z]{2,}", target
    )
    if looks_like_prose:
        return extract_answer(target) or normalize_answer(target)
    return normalize_answer(target)


def process_results(answer: str, solution: str) -> tuple[int, tuple[str, str]]:
    """Grade a full completion against a ground-truth solution string,
    returning (0/1, (extracted_pred, extracted_gt)) — the reference's
    batch-eval entry point (math_parser.py:759)."""
    gt = _extract_ground_truth(solution)
    pred = extract_answer(answer)
    if pred is None:
        return 0, ("", gt)
    return int(math_equal(pred, gt)), (pred, gt)
