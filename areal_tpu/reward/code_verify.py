"""Code-verifier reward: batched sandboxed testcase execution.

Parity: /root/reference/functioncall/code/verify.py:111 `code_verify` —
the coding-RL reward behind the reference's LCB numbers. Problems carry an
`input_output` JSON blob ({"inputs": [...], "outputs": [...], "fn_name":
optional}); candidate code passes iff every testcase passes. Each problem
runs in its own killed-on-timeout subprocess (areal_tpu/reward/_code_runner)
with rlimits; problems verify concurrently in a thread pool (the TPU-host
analogue of the reference's remote batched function-call service).

Reward-fn surface (`code_reward_fn`) follows the RLVR signature so the
existing RLVRWorkflow runs coding RL unchanged:

    workflow = RLVRWorkflow(reward_fn=code_reward_fn, gconfig=...)

with dataset items providing `input_output` (and optionally `timeout`,
`memory`, `query_id`).
"""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
from concurrent.futures import ThreadPoolExecutor
from typing import Any

from areal_tpu.utils import logging

logger = logging.getLogger("code_verify")

SINGLE_CASE_EXEC_TIMEOUT = 6.0  # parity: verify.py SINGLE_CASE_EXEC_TIMEOUT
FUNCTIONCALL_TIMEOUT = 100.0  # parity: verify.py FUNCTIONCALL_TIMEOUT
_CODE_BLOCK = re.compile(r"```(?:python|py)?\s*\n(.*?)```", re.DOTALL)


def extract_code(completion: str) -> str | None:
    """Last fenced code block (models emit reasoning first, code last)."""
    blocks = _CODE_BLOCK.findall(completion or "")
    if blocks:
        return blocks[-1].strip()
    return None


def run_problem(
    code: str,
    input_output: dict[str, Any],
    *,
    timeout_per_case: float = SINGLE_CASE_EXEC_TIMEOUT,
    total_timeout: float = FUNCTIONCALL_TIMEOUT,
    memory_mb: int = 0,
) -> bool:
    """Run one candidate against one problem's testcases in a sandbox
    subprocess; True iff every case passed."""
    asserts = input_output.get("asserts") or []
    inputs = input_output.get("inputs", [])
    outputs = input_output.get("outputs", [])
    if asserts:
        # HumanEval/MBPP-style unit-test harnesses: each case is a code
        # snippet (assert statement or check(candidate) driver) exec'd in
        # the candidate's namespace
        testcases: list[dict] = [
            {"input": "", "expectedOutput": "", "assertCode": a}
            for a in asserts
        ]
    else:
        if len(inputs) != len(outputs):
            raise ValueError(
                f"inputs({len(inputs)})/outputs({len(outputs)}) mismatch"
            )
        if not inputs:
            return False  # no testcases of either style: nothing to verify
        testcases = [
            {"input": i, "expectedOutput": o} for i, o in zip(inputs, outputs)
        ]
    spec = dict(
        code=code,
        entryFunction=input_output.get("fn_name", ""),
        testcases=testcases,
        timeout=min(100.0, max(0.1, timeout_per_case)),
        memory=memory_mb,
        isFastFail=True,
    )
    proc = subprocess.Popen(
        [sys.executable, "-m", "areal_tpu.reward._code_runner"],
        stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        start_new_session=True,  # own group → clean kill of forks
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
    )
    try:
        out, _ = proc.communicate(
            json.dumps(spec).encode(), timeout=total_timeout
        )
    except subprocess.TimeoutExpired:
        _kill_group(proc)
        return False
    except Exception:  # noqa: BLE001 — verifier must never crash the loop
        _kill_group(proc)
        return False
    try:
        verdict = json.loads(out.decode() or "{}")
    except json.JSONDecodeError:
        return False
    results = verdict.get("results", [])
    return len(results) == len(testcases) and all(results)


def _kill_group(proc: subprocess.Popen) -> None:
    try:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass


def code_verify(
    id2info: dict[str, dict],
    generateds: list[str],
    query_ids: list[str],
    *,
    timeout: float = FUNCTIONCALL_TIMEOUT,
    timeout_for_testcase: float = SINGLE_CASE_EXEC_TIMEOUT,
    max_workers: int = 8,
) -> list[int]:
    """Batched verification (parity: verify.py:111 code_verify).

    Returns one 0/1 per query, order-aligned with `query_ids`.
    """
    assert len(generateds) == len(query_ids), (len(generateds), len(query_ids))

    def one(idx: int) -> int:
        problem = id2info[query_ids[idx]]
        io_blob = problem["input_output"]
        input_output = (
            json.loads(io_blob) if isinstance(io_blob, str) else io_blob
        )
        per_case = min(
            100.0,
            max(0.1, float(problem.get("timeout", timeout_for_testcase)) * 1.5),
        )
        try:
            ok = run_problem(
                generateds[idx] or "",
                input_output,
                timeout_per_case=per_case,
                total_timeout=timeout,
                memory_mb=int(problem.get("memory", 0)),
            )
        except Exception as e:  # noqa: BLE001 — one bad problem ≠ dead batch
            logger.warning(f"code_verify failed for {query_ids[idx]}: {e!r}")
            ok = False
        return int(ok)

    with ThreadPoolExecutor(max_workers=max_workers) as pool:
        return list(pool.map(one, range(len(query_ids))))


def code_reward_fn(prompt, completion, prompt_ids, completion_ids, **data):
    """RLVR reward: 1.0 iff the completion's code passes every testcase.

    Dataset items supply `input_output` (dict or JSON string) and optional
    `timeout`/`memory` — the reference's coding-problem schema.
    """
    code = extract_code(completion or "")
    if code is None:
        return 0.0
    io_blob = data.get("input_output")
    if io_blob is None:
        return 0.0
    input_output = json.loads(io_blob) if isinstance(io_blob, str) else io_blob
    per_case = min(
        100.0,
        max(0.1, float(data.get("timeout", SINGLE_CASE_EXEC_TIMEOUT)) * 1.5),
    )
    return float(
        run_problem(
            code,
            input_output,
            timeout_per_case=per_case,
            memory_mb=int(data.get("memory", 0)),
        )
    )


def code_eval_reward_fn(prompt, completion, prompt_ids, completion_ids, **data):
    """Completion-style code-benchmark reward (HumanEval/MBPP pass@k).

    Candidate assembly follows the Codex eval convention: a fenced code
    block wins if present (chat models); otherwise the completion is a raw
    CONTINUATION of the item's `code_prompt` (the classic HumanEval
    function-signature prefix). The item's `input_output.asserts` harness
    runs in the sandbox subprocess (reward/_code_runner assert mode).
    """
    io_blob = data.get("input_output")
    if io_blob is None:
        return 0.0
    input_output = json.loads(io_blob) if isinstance(io_blob, str) else io_blob
    code = extract_code(completion or "")
    if code is None:
        code = str(data.get("code_prompt", "")) + (completion or "")
    per_case = min(
        100.0,
        max(0.1, float(data.get("timeout", SINGLE_CASE_EXEC_TIMEOUT)) * 1.5),
    )
    return float(
        run_problem(
            code,
            input_output,
            timeout_per_case=per_case,
            memory_mb=int(data.get("memory", 0)),
        )
    )
