"""Remote batch verification: the scale-out reward seam.

Parity: /root/reference/functioncall/ — the reference offloads math/code
grading to an HTTP "functioncall" service (base/call.py batch_function_call:
batched POSTs, bounded concurrency, retries) when
FUNCTIONCALL_SERVICE_DOMAIN is set, else grades locally. Heavy RL runs need
this: sympy/subprocess grading of thousands of samples per step would
otherwise serialize on the trainer host.

This module ships BOTH ends:
- `batch_math_verify` / `batch_code_verify`: clients that POST to the
  service named by AREAL_VERIFIER_SERVICE (FUNCTIONCALL_SERVICE_DOMAIN is
  honoured for reference-compat) in bounded-concurrency batches with
  retries, falling back to the local graders (areal_tpu.reward.math_parser
  / code_verify) in a thread pool when unset or unreachable.
- `VerifyServer` (`python -m areal_tpu.reward.verify_server`): the service
  itself — an aiohttp app running the same local graders, horizontally
  scalable on CPU hosts (the reference assumes an external deployment and
  ships only the client).

Protocol: POST /verify {"uid", "language": "MATH"|"CODE", "payload": ...}
-> {"results": [0/1, ...]} aligned with the payload order.
"""

from __future__ import annotations

import asyncio
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Any

from areal_tpu.utils import logging

logger = logging.getLogger("remote_verify")

_BATCH_SIZE = 10
_CONCURRENCY = 64
_RETRIES = 3


def service_addr() -> str | None:
    return (
        os.environ.get("AREAL_VERIFIER_SERVICE")
        or os.environ.get("FUNCTIONCALL_SERVICE_DOMAIN")
        or None
    )


# ---------------------------------------------------------------------------
# local grading (the fallback AND the server's engine)
# ---------------------------------------------------------------------------


def _grade_math_pair(answer: str, solution: str) -> int:
    from areal_tpu.reward.math_parser import (
        _extract_ground_truth,
        extract_answer,
        math_equal_subprocess,
    )

    pred = extract_answer(answer)  # extraction is regex-only — no sympy
    if pred is None:
        return 0
    # the SUBPROCESS grader: adversarial sympy inputs hit its hard timeout
    # instead of permanently wedging a grader thread (and, transitively,
    # the verify service's whole worker pool)
    return int(
        math_equal_subprocess(
            pred, _extract_ground_truth(str(solution)), timeout_s=5.0
        )
    )


def grade_math_batch(answers: list[str], solutions: list[str]) -> list[int]:
    """Pairwise grading, order-aligned."""
    return [_grade_math_pair(a, s) for a, s in zip(answers, solutions)]


def grade_code_batch(items: list[dict[str, Any]]) -> list[int]:
    """Each item: {"completion": str, "input_output": {...}}."""
    from areal_tpu.reward.code_verify import extract_code, run_problem

    out = []
    for item in items:
        code = extract_code(item.get("completion", ""))
        io_spec = item.get("input_output") or {}
        out.append(int(bool(code) and run_problem(code, io_spec)))
    return out


# ---------------------------------------------------------------------------
# batch client
# ---------------------------------------------------------------------------


async def _post_batches(
    addr: str, payloads: list[dict], timeout_s: float
) -> list[list[int]] | None:
    """POST every payload; None on unrecoverable transport failure (the
    caller falls back to local grading — a broken service must degrade,
    not zero out rewards)."""
    import aiohttp

    url = addr if addr.startswith("http") else f"http://{addr}"
    sem = asyncio.Semaphore(_CONCURRENCY)
    timeout = aiohttp.ClientTimeout(total=timeout_s)

    async with aiohttp.ClientSession(timeout=timeout) as session:

        async def one(payload: dict) -> list[int] | None:
            async with sem:
                last = "unknown"
                for attempt in range(_RETRIES):
                    try:
                        async with session.post(
                            f"{url}/verify", json=payload
                        ) as resp:
                            if resp.status == 200:
                                data = await resp.json()
                                return [int(r) for r in data["results"]]
                            last = f"status {resp.status}"
                    except Exception as e:  # noqa: BLE001 — retry then fail
                        last = repr(e)
                    if attempt < _RETRIES - 1:  # no dead wait after final try
                        await asyncio.sleep(0.2 * (attempt + 1))
                logger.warning(f"verify service call failed: {last}")
                return None

        results = await asyncio.gather(*[one(p) for p in payloads])
    if any(r is None for r in results):
        return None
    return list(results)  # type: ignore[arg-type]


def _run_async(coro):
    """Client entry points are sync (reward fns run in worker threads);
    always use a private loop so a caller's running loop is untouched."""
    return asyncio.run(coro)


def batch_math_verify(
    id2info: dict[str, dict],
    generateds: list[str],
    query_ids: list[str],
    *,
    timeout_s: float = 1000.0,
    max_workers: int = 8,
) -> list[int]:
    """One 0/1 per generated, order-aligned (parity:
    functioncall/math/verify.py math_verify): a sample passes if it
    matches ANY of its question's solutions."""
    assert len(generateds) == len(query_ids)
    pairs: list[tuple[str, str, int]] = []  # (answer, solution, sample idx)
    for idx, (qid, gen) in enumerate(zip(query_ids, generateds)):
        info = id2info[str(qid).split("@")[0]]
        for sol in info.get("solutions") or [info.get("answer", "")]:
            pairs.append((gen, str(sol), idx))

    addr = service_addr()
    flat: list[int] | None = None
    if addr:
        payloads = []
        for i in range(0, len(pairs), _BATCH_SIZE):
            chunk = pairs[i : i + _BATCH_SIZE]
            payloads.append(
                {
                    "uid": f"math-{i}-{i + len(chunk)}",
                    "language": "MATH",
                    "payload": {
                        "answers": [a for a, _, _ in chunk],
                        "solutions": [s for _, s, _ in chunk],
                    },
                }
            )
        per_batch = _run_async(_post_batches(addr, payloads, timeout_s))
        if per_batch is not None:
            flat = [r for batch in per_batch for r in batch]
    if flat is None:  # no service / service down: grade locally in threads
        with ThreadPoolExecutor(max_workers=max_workers) as pool:
            flat = list(
                pool.map(lambda p: _grade_math_pair(p[0], p[1]), pairs)
            )

    results = [0] * len(generateds)
    for (_, _, idx), ok in zip(pairs, flat):
        results[idx] = max(results[idx], int(ok))
    return results


def batch_code_verify(
    id2info: dict[str, dict],
    generateds: list[str],
    query_ids: list[str],
    *,
    timeout_s: float = 1000.0,
    max_workers: int = 8,
) -> list[int]:
    """One 0/1 per generated, order-aligned (parity:
    functioncall/code/verify.py code_verify)."""
    assert len(generateds) == len(query_ids)
    items = []
    for qid, gen in zip(query_ids, generateds):
        info = id2info[str(qid).split("@")[0]]
        items.append(
            {"completion": gen, "input_output": info.get("input_output") or {}}
        )

    addr = service_addr()
    if addr:
        payloads = []
        for i in range(0, len(items), _BATCH_SIZE):
            chunk = items[i : i + _BATCH_SIZE]
            payloads.append(
                {
                    "uid": f"code-{i}-{i + len(chunk)}",
                    "language": "CODE",
                    "payload": {"items": chunk},
                }
            )
        per_batch = _run_async(_post_batches(addr, payloads, timeout_s))
        if per_batch is not None:
            return [r for batch in per_batch for r in batch]
    with ThreadPoolExecutor(max_workers=max_workers) as pool:
        return list(pool.map(lambda it: grade_code_batch([it])[0], items))
