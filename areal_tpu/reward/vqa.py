"""Vision RLVR rewards (parity: areal/reward/{clevr_count_70k,geometry3k}.py).

Both extract the model's final answer (boxed or <answer> tag or trailing
token) and compare against the ground truth: exact count match for CLEVR
counting, math/choice equivalence for Geometry3K.
"""

from __future__ import annotations

import re

from areal_tpu.reward.math_parser import extract_answer, math_equal


def _extract(completion: str) -> str | None:
    m = re.search(r"<answer>(.*?)</answer>", completion, re.DOTALL)
    if m:
        return m.group(1).strip()
    return extract_answer(completion)


def clevr_count_reward(
    prompt, completion, prompt_ids=None, completion_ids=None, **data
) -> float:
    """Binary reward: predicted object count equals the label."""
    target = data.get("answer")
    if completion is None or target is None:
        return 0.0
    pred = _extract(completion)
    if pred is None:
        return 0.0
    digits = re.findall(r"-?\d+", pred)
    tdigits = re.findall(r"-?\d+", str(target))
    if not digits or not tdigits:
        return 0.0
    return 1.0 if int(digits[-1]) == int(tdigits[-1]) else 0.0


def geometry3k_reward(
    prompt, completion, prompt_ids=None, completion_ids=None, **data
) -> float:
    """Binary reward: answer equivalent to ground truth (numeric/symbolic
    via the math parser; falls back to case-insensitive string match for
    multiple-choice letters)."""
    target = data.get("answer")
    if completion is None or target is None:
        return 0.0
    pred = _extract(completion)
    if pred is None:
        return 0.0
    t = str(target).strip()
    if math_equal(pred, t):
        return 1.0
    return 1.0 if pred.strip().lower() == t.lower() else 0.0


def synthetic_vision_reward(
    prompt, completion, prompt_ids=None, completion_ids=None, **data
) -> float:
    """Offline smoke reward for the synthetic-vision dataset: the label
    count (1-4) must appear among the generated token IDS — the smoke
    decoder has no numeral text, so token identity stands in for the
    decoded answer digit (cf. dataset/arith.py's string-level reward)."""
    target = data.get("answer")
    if not completion_ids or target is None:
        return 0.0
    return 1.0 if int(target) in list(completion_ids) else 0.0
