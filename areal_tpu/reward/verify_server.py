"""Standalone verification service (the server half of remote_verify.py).

Run ``python -m areal_tpu.reward.verify_server --port 8841`` on any CPU
host and point trainers at it with
``AREAL_VERIFIER_SERVICE=host:8841`` — math (sympy) and code (sandboxed
subprocess testcases) grading then runs off the TPU host. The reference
only ships the client against an assumed external "functioncall"
deployment (/root/reference/functioncall/base/call.py:21); this service is
the deployable counterpart.

Endpoints:
  GET  /health  -> {"status": "ok"}
  POST /verify  {"uid", "language": "MATH"|"CODE", "payload": ...}
                -> {"results": [0/1, ...]}
"""

from __future__ import annotations

import argparse
import asyncio

from aiohttp import web

from areal_tpu.utils import logging

logger = logging.getLogger("verify_server")


class VerifyServer:
    def __init__(self, max_workers: int = 8):
        from concurrent.futures import ThreadPoolExecutor

        self._pool = ThreadPoolExecutor(max_workers=max_workers)
        self._runner: web.AppRunner | None = None
        self.addr: str | None = None

    async def _health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})

    async def _verify(self, request: web.Request) -> web.Response:
        from areal_tpu.reward.remote_verify import (
            grade_code_batch,
            grade_math_batch,
        )

        body = await request.json()
        lang = str(body.get("language", "")).upper()
        payload = body.get("payload") or {}
        loop = asyncio.get_running_loop()
        try:
            if lang == "MATH":
                results = await loop.run_in_executor(
                    self._pool,
                    grade_math_batch,
                    payload["answers"],
                    payload["solutions"],
                )
            elif lang == "CODE":
                results = await loop.run_in_executor(
                    self._pool, grade_code_batch, payload["items"]
                )
            else:
                return web.json_response(
                    {"status": "error", "message": f"unknown language {lang}"},
                    status=400,
                )
        except Exception as e:  # noqa: BLE001 — report, don't crash
            return web.json_response(
                {"status": "error", "message": repr(e)}, status=500
            )
        return web.json_response({"uid": body.get("uid"), "results": results})

    def build_app(self) -> web.Application:
        app = web.Application(client_max_size=64 * 1024**2)
        app.router.add_get("/health", self._health)
        app.router.add_post("/verify", self._verify)
        return app

    async def start(self, host: str = "0.0.0.0", port: int = 0) -> str:
        self._runner = web.AppRunner(self.build_app())
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        actual_port = self._runner.addresses[0][1]
        self.addr = f"127.0.0.1:{actual_port}" if host in ("0.0.0.0", "::") else f"{host}:{actual_port}"
        logger.info(f"verify server on {self.addr}")
        return self.addr

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
        self._pool.shutdown(wait=False)


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description="areal_tpu verification service")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8841)
    p.add_argument("--max-workers", type=int, default=8)
    args = p.parse_args(argv)

    async def serve():
        srv = VerifyServer(max_workers=args.max_workers)
        await srv.start(args.host, args.port)
        await asyncio.Event().wait()

    asyncio.run(serve())


if __name__ == "__main__":
    main()
